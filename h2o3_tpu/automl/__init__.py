"""AutoML — budgeted multi-algorithm search + stacked ensembles.

Reference: ai/h2o/automl/AutoML.java:49 — planWork (AutoML.java:420)
allocates a budget across modeling steps from ModelingStepsProviders
(modeling/{GLM,GBM,DRF,DeepLearning,StackedEnsemble,...}StepsProvider),
learn (AutoML.java:760) executes defaults then random grids under
max_models / max_runtime_secs, every model cross-validated, results
ranked in hex.leaderboard.Leaderboard, StackedEnsemble best-of-family +
all-models trained last.

Same plan here; every candidate trains with nfolds CV on the full mesh.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.ml.ensemble import StackedEnsembleEstimator
from h2o3_tpu.ml.grid import GridSearch
from h2o3_tpu.ml.leaderboard import Leaderboard
from h2o3_tpu.models import get_builder
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.automl")


def _default_steps(seed: int) -> List[dict]:
    """The modeling plan (modeling/*StepsProvider defaults, in the
    reference's execution order: defaults first, then grids)."""
    return [
        {"algo": "glm", "name": "GLM_1",
         "params": {"family": "auto", "lambda_search": True, "nlambdas": 10}},
        {"algo": "gbm", "name": "GBM_1",
         "params": {"ntrees": 50, "max_depth": 6, "learn_rate": 0.1,
                    "sample_rate": 0.8, "col_sample_rate_per_tree": 0.8,
                    "seed": seed}},
        {"algo": "gbm", "name": "GBM_2",
         "params": {"ntrees": 60, "max_depth": 7, "learn_rate": 0.08,
                    "sample_rate": 0.9, "seed": seed + 1}},
        {"algo": "gbm", "name": "GBM_3",
         "params": {"ntrees": 40, "max_depth": 4, "learn_rate": 0.15,
                    "seed": seed + 2}},
        {"algo": "drf", "name": "DRF_1",
         "params": {"ntrees": 50, "max_depth": 12, "seed": seed}},
        {"algo": "deeplearning", "name": "DeepLearning_1",
         "params": {"hidden": [64, 64], "epochs": 10, "seed": seed,
                    "stopping_rounds": 3}},
        {"grid": True, "algo": "gbm", "name": "GBM_grid_1",
         "hyper": {"max_depth": [3, 5, 7, 9],
                   "learn_rate": [0.05, 0.1, 0.2],
                   "sample_rate": [0.7, 0.9, 1.0]},
         "params": {"ntrees": 40, "seed": seed}},
    ]


class H2OAutoML:
    """h2o-py H2OAutoML-compatible surface (h2o-py/h2o/automl/).

    ``keep_cross_validation_predictions`` is effectively always True here
    (holdouts are kept in-memory for stacking); ``balance_classes`` is not
    implemented and warns if set; ``verbosity`` only affects logging.
    """

    def __init__(self, max_models: int = 0, max_runtime_secs: float = 3600.0,
                 seed: int = -1, nfolds: int = 5,
                 project_name: Optional[str] = None,
                 sort_metric: Optional[str] = None,
                 include_algos: Optional[Sequence[str]] = None,
                 exclude_algos: Optional[Sequence[str]] = None,
                 stopping_rounds: int = 3, stopping_tolerance: float = 1e-3,
                 keep_cross_validation_predictions: bool = True,
                 verbosity: str = "warn", balance_classes: bool = False,
                 max_runtime_secs_per_model: float = 0.0):
        self.max_models = int(max_models)
        self.max_runtime_secs = float(max_runtime_secs)
        self.seed = int(seed) if int(seed) >= 0 else 5723
        self.nfolds = int(nfolds)
        self.project_name = project_name or f"automl_{int(time.time())}"
        self.sort_metric = sort_metric
        self.include = ({a.lower() for a in include_algos}
                        if include_algos else None)
        self.exclude = {a.lower() for a in (exclude_algos or ())}
        self.leaderboard_obj = Leaderboard(self.project_name, sort_metric)
        self.stopping_rounds = int(stopping_rounds)
        self.stopping_tolerance = float(stopping_tolerance)
        self.max_runtime_secs_per_model = float(max_runtime_secs_per_model)
        if balance_classes:
            log.warning("balance_classes is not implemented; ignoring")

    # -- helpers -------------------------------------------------------
    def _allowed(self, algo: str) -> bool:
        a = algo.lower()
        if self.include is not None and a not in self.include:
            return False
        return a not in self.exclude

    @property
    def leader(self):
        return self.leaderboard_obj.leader

    @property
    def leaderboard(self):
        return self.leaderboard_obj

    def predict(self, frame: Frame) -> Frame:
        return self.leader.predict(frame)

    # -- train ---------------------------------------------------------
    def train(self, y: str, training_frame: Frame,
              x: Optional[Sequence[str]] = None,
              validation_frame: Optional[Frame] = None,
              leaderboard_frame: Optional[Frame] = None):
        t0 = time.time()
        deadline = (t0 + self.max_runtime_secs
                    if self.max_runtime_secs else None)
        steps = _default_steps(self.seed)
        budget_models = self.max_models or 10 ** 9
        trained: List = []

        def out_of_budget():
            if len(trained) >= budget_models:
                return True
            return deadline is not None and time.time() > deadline

        for step in steps:
            algo = step["algo"]
            if not self._allowed(algo) or out_of_budget():
                continue
            try:
                if step.get("grid"):
                    remaining = budget_models - len(trained)
                    budget_s = (max(0.0, deadline - time.time())
                                if deadline else 0)
                    gs = GridSearch(
                        get_builder(algo),
                        step["hyper"],
                        search_criteria={"strategy": "RandomDiscrete",
                                         "max_models": min(remaining, 5),
                                         "max_runtime_secs": budget_s,
                                         "seed": self.seed},
                        **{**step["params"], "nfolds": self.nfolds})
                    grid = gs.train(training_frame, y=y, x=x)
                    for m in grid.models:
                        m.output["automl_step"] = step["name"]
                    trained.extend(grid.models)
                    self.leaderboard_obj.add(*grid.models)
                else:
                    params = {**step["params"], "nfolds": self.nfolds}
                    # wire AutoML early stopping into builders that take it
                    cls = get_builder(algo)
                    if "stopping_rounds" in cls.DEFAULTS:
                        params.setdefault("stopping_rounds",
                                          self.stopping_rounds)
                        params.setdefault("stopping_tolerance",
                                          self.stopping_tolerance)
                    m = cls(**params).train(training_frame, y=y, x=x)
                    m.output["automl_step"] = step["name"]
                    trained.append(m)
                    self.leaderboard_obj.add(m)
                log.info("automl: %s done (%d models, %.0fs elapsed)",
                         step["name"], len(trained), time.time() - t0)
            except Exception as e:
                log.warning("automl step %s failed: %s", step["name"], e)

        # stacked ensembles last (StackedEnsembleStepsProvider):
        # best-of-family + all-models
        with_cv = [m for m in trained
                   if getattr(m, "_cv_holdout", None) is not None]
        best_of_family = {}
        if self._allowed("stackedensemble") and len(with_cv) >= 2:
            for m in self.leaderboard_obj.sorted_models():
                if m in with_cv and m.algo not in best_of_family:
                    best_of_family[m.algo] = m
            if len(best_of_family) >= 2:
                try:
                    se = StackedEnsembleEstimator(
                        base_models=list(best_of_family.values())).train(
                        training_frame, y=y, x=x)
                    se.output["automl_step"] = "StackedEnsemble_BestOfFamily"
                    self.leaderboard_obj.add(se)
                except Exception as e:
                    log.warning("automl best-of-family ensemble failed: %s", e)
            if len(with_cv) > max(2, len(best_of_family)):
                try:
                    se2 = StackedEnsembleEstimator(
                        base_models=with_cv[:10]).train(
                        training_frame, y=y, x=x)
                    se2.output["automl_step"] = "StackedEnsemble_AllModels"
                    self.leaderboard_obj.add(se2)
                except Exception as e:
                    log.warning("automl all-models ensemble failed: %s", e)

        log.info("automl done: %d models in %.0fs; leader=%s",
                 len(self.leaderboard_obj.models), time.time() - t0,
                 self.leader.key if self.leader else None)
        return self.leader
