"""Pallas TPU kernel for the tree-building histogram.

The XLA path (ops/histogram.py) expresses the (node, feature, bin)
accumulation as one-hot × stats matmuls; XLA materializes the [C, F·B]
one-hot indicator between fusions, so every row block round-trips an
inflated intermediate through HBM. This kernel builds the indicators
in VMEM, feeds the MXU directly, and accumulates the histogram in a
VMEM scratch across the row-block grid — the whole hot loop of
ScoreBuildHistogram2 (hex/tree/DHistogram.java:585-674) stays on-chip.

Layout per grid step i over row blocks of C rows:
    bins_blk  [C, F] int32      (feature-bin ids; NA bin = B-1)
    nid_blk   [C, 1] int32      (current leaf per row)
    stats_blk [C, 3] f32        ({w, w·g, w·h}; 0 on padding rows)
    right     [C, F·B]  = one-hot(bins)       built in VMEM
    left      [C, 3L]   = one-hot(nid) ⊗ stats
    acc      += leftᵀ @ right                  (MXU, f32)
Final step writes acc → out [3L, F·B]; caller reshapes to [L, F, B, 3].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _hist_kernel(bins_ref, nid_ref, stats_ref, out_ref, acc_ref, *,
                 n_nodes: int, n_bins: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    bins = bins_ref[:]                     # [C, F]
    C, F = bins.shape
    FB = F * n_bins
    # combined (feature, bin) id per row/feature; one-hot built with an
    # unrolled per-feature compare against the lane iota — Mosaic has no
    # minor-dim reshape, so [C,F,B]→[C,FB] is constructed directly
    feat_off = jax.lax.broadcasted_iota(jnp.int32, (C, F), 1) * n_bins
    fb = bins + feat_off                   # [C, F] in [0, FB)
    lane = jax.lax.broadcasted_iota(jnp.int32, (C, FB), 1)
    right = (lane == fb[:, 0:1]).astype(jnp.float32)
    for f in range(1, F):
        right += (lane == fb[:, f:f + 1]).astype(jnp.float32)

    # left [C, 3L] with column k ↦ (node k//3, stat k%3), built without
    # any minor-dim reshape (Mosaic-unsupported): three masked
    # broadcast-multiplies against the lane iota
    nid = nid_ref[:]                       # [C, 1]
    stats = stats_ref[:]                   # [C, 3]
    lane3 = jax.lax.broadcasted_iota(jnp.int32, (C, n_nodes * 3), 1)
    node_of_k = lane3 // 3
    stat_of_k = lane3 - 3 * node_of_k
    node_hit = (nid == node_of_k).astype(jnp.float32)        # [C, 3L]
    left = jnp.zeros((C, n_nodes * 3), jnp.float32)
    for s in range(3):
        sel = (stat_of_k == s).astype(jnp.float32)
        left += sel * node_hit * stats[:, s:s + 1]

    acc_ref[:] += jax.lax.dot_general(
        left, right, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


def pallas_local_histogram(bins, nid, stats, n_nodes: int, n_bins: int,
                           block_rows: int = 512, interpret: bool = False):
    """Single-shard histogram [L, F, B, 3] via the Pallas kernel.

    Drop-in replacement for ops/histogram._local_histogram on TPU
    backends (CPU tests run it with interpret=True).
    """
    from h2o3_tpu.ops import pallas as pallas_policy
    pallas_policy.record_launch("histogram")
    N, F = bins.shape
    C = min(block_rows, N)
    nblk = (N + C - 1) // C
    Npad = nblk * C
    if Npad != N:   # padding rows carry zero stats → no contribution
        bins = jnp.pad(bins, ((0, Npad - N), (0, 0)))
        nid = jnp.pad(nid, (0, Npad - N))
        stats = jnp.pad(stats, ((0, Npad - N), (0, 0)))

    kern = functools.partial(_hist_kernel, n_nodes=n_nodes, n_bins=n_bins)
    out = pl.pallas_call(
        kern,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((C, F), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((C, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((C, 3), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((n_nodes * 3, F * n_bins), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_nodes * 3, F * n_bins),
                                       jnp.float32),
        scratch_shapes=[pltpu.VMEM((n_nodes * 3, F * n_bins), jnp.float32)],
        interpret=interpret,
    )(bins, nid.reshape(-1, 1), stats)
    return out.reshape(n_nodes, 3, F, n_bins).transpose(0, 2, 3, 1)
