"""Device sort-merge equi-join — the BinaryMerge.java role, TPU-native.

Reference contract: water/rapids/RadixOrder.java + BinaryMerge.java —
MSD-radix order both sides, then per-key binary search with per-row
match-range expansion. The TPU collapse keeps ALL the heavy work on
device in three compiled programs:

  1. ``_match_ranges``: one multi-key lexicographic sort of the
     CONCATENATED left+right keys (repeated stable argsort — the XLA
     sort network is the radix order), equal-key runs found with one
     shifted-compare, per-run right-row counts via ``segment_sum``.
     Multi-key equality needs no 64-bit key packing (x64 is off) —
     each key column is compared in its own dtype.
  2. ``_gather_out``: static-shape expansion of the per-left-row match
     ranges (searchsorted over the match-count prefix sum) + gathers of
     every output column, NA-masking unmatched right rows for left
     joins.

All three run on the frames' PADDED device arrays with the valid row
counts as TRACED scalars, so one compiled pipeline serves every frame
pair whose padded (bucketed) shapes match — the same compile economics
as mesh.padded_rows. The controller only touches ONE scalar (the total
match count, needed to size program 3). Host numpy remains the
tiny-frame path — sub-64K pyunit frames pay more in compile than they
save.

NA keys never match (Merge.java semantics). For all-float keys NA
folds to NaN: jnp.argsort orders finite < +inf < NaN and NaN != NaN
isolates every NA row in its own run, so genuine +inf keys still match
each other while NA rows match nothing — no sentinel collisions and no
extra sort pass. Mixed int keys keep an explicit NA ordering pass.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.column import Column
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.parallel import mesh as mesh_mod
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.merge")

DEVICE_MERGE_MIN_ROWS = 65536


def _merge_out_budget() -> int:
    """Max bytes the device join result may occupy: half the governor's
    HBM budget (core/memgov.py — device bytes_limit, else the
    H2O3TPU_HBM_BUDGET_MB knob). Without any budget source, CPU meshes
    (the 8-virtual-device test topology, usually on a small host) get a
    conservative 2GB and accelerators the shared 16GB assumption for
    plugins exporting no memory stats (axon)."""
    import os
    env = os.environ.get("H2O3TPU_MERGE_MAX_OUT_BYTES")
    if env:
        return int(env)
    from h2o3_tpu.core import memgov
    lim = memgov.governor.device_limit_bytes()
    if lim:
        return int(lim * 0.5)
    # the mesh's devices, NOT jax.devices(): the axon plugin shadows
    # JAX_PLATFORMS, so jax.devices() reports the tunneled chip even
    # when the cloud (and this merge) runs on the CPU mesh
    dev = mesh_mod.get_mesh().devices.flat[0]
    if dev.platform == "cpu":
        return 2 << 30
    return memgov.DEFAULT_DEVICE_HBM_BYTES


def _all_float(keys) -> bool:
    return all(jnp.issubdtype(k.dtype, jnp.floating) for k in keys)


@partial(jax.jit, static_argnames=("n_keys",))
def _match_ranges(l_keys, l_nas, r_keys, r_nas, l_valid, r_valid, *,
                  n_keys: int):
    """Per-left-row [lo, lo+cnt) match ranges into right-sorted order.

    One combined sort of both (padded) sides; a run = maximal block of
    equal key tuples; each left row's matches are the right rows of its
    run. NA/padding rows never match: they fold to NaN (all-float keys,
    each NaN its own run) or sort into an explicitly-separated tail
    block (int keys) and left-NA counts are zeroed either way.
    """
    Lp = l_keys[0].shape[0]
    Rp = r_keys[0].shape[0]
    N = Lp + Rp
    l_pad = jnp.arange(Lp, dtype=jnp.int32) >= l_valid
    r_pad = jnp.arange(Rp, dtype=jnp.int32) >= r_valid
    comb, na_any = [], jnp.concatenate([l_pad, r_pad])
    for j in range(n_keys):
        k = jnp.concatenate([l_keys[j], r_keys[j]])
        na = jnp.concatenate([l_nas[j], r_nas[j]])
        if jnp.issubdtype(k.dtype, jnp.floating):
            na = na | jnp.isnan(k)
        na_any = na_any | na
        comb.append(k)
    fold_nan = _all_float(comb)
    if fold_nan:
        comb = [jnp.where(na_any, jnp.nan, k) for k in comb]
    else:
        comb = [jnp.where(na_any, jnp.zeros((), k.dtype), k) for k in comb]
    side = jnp.concatenate([jnp.zeros(Lp, jnp.int8), jnp.ones(Rp, jnp.int8)])

    order = jnp.arange(N, dtype=jnp.int32)
    for j in range(n_keys - 1, -1, -1):
        order = order[jnp.argsort(comb[j][order], stable=True)]
    if not fold_nan:
        order = order[jnp.argsort(na_any[order].astype(jnp.int8),
                                  stable=True)]

    s_na = na_any[order]
    s_side = side[order]
    pos = jnp.arange(N, dtype=jnp.int32)
    new_run = jnp.zeros(N, bool)
    for k in comb:
        sk = k[order]
        neq = sk != jnp.roll(sk, 1)
        if jnp.issubdtype(sk.dtype, jnp.floating):
            # NaN != NaN is True — exactly what isolates NA rows
            neq = neq | jnp.isnan(sk)
        new_run = new_run | neq
    new_run = new_run | (s_na != jnp.roll(s_na, 1))
    new_run = new_run.at[0].set(True)
    run_id = (jnp.cumsum(new_run.astype(jnp.int32)) - 1).astype(jnp.int32)
    seg_right = jax.ops.segment_sum(s_side.astype(jnp.int32), run_id,
                                    num_segments=N)
    cnt_at_pos = seg_right[run_id]
    rights_before = jnp.cumsum(s_side.astype(jnp.int32)) - s_side
    run_start = jax.lax.cummax(jnp.where(new_run, pos, 0))
    lo_at_pos = rights_before[run_start]
    cnt_at_pos = jnp.where(s_na, 0, cnt_at_pos)

    is_left = s_side == 0
    # left rows were concatenated first: their combined index IS the
    # original left row; rights scatter into the dump slot Lp
    l_orig = jnp.where(is_left, order, Lp)
    out_lo = jnp.zeros(Lp + 1, jnp.int32).at[l_orig].set(
        lo_at_pos.astype(jnp.int32))
    out_cnt = jnp.zeros(Lp + 1, jnp.int32).at[l_orig].set(
        cnt_at_pos.astype(jnp.int32))
    # right-sorted order falls out of the SAME sort (no second lexsort):
    # the right row at combined position p lands at rank rights_before[p]
    r_rank = jnp.where(is_left, Rp, rights_before)
    r_order = jnp.zeros(Rp + 1, jnp.int32).at[r_rank].set(
        jnp.where(is_left, 0, order - Lp).astype(jnp.int32))
    return out_lo[:Lp], out_cnt[:Lp], r_order[:Rp]


@jax.jit
def _total_rows(cnt, l_valid):
    """(left-join total, inner total) as device scalars."""
    valid = jnp.arange(cnt.shape[0], dtype=jnp.int32) < l_valid
    return jnp.sum(jnp.where(valid, jnp.maximum(cnt, 1), 0)), \
        jnp.sum(jnp.where(valid, cnt, 0))


@partial(jax.jit,
         static_argnames=("out_n", "left_join", "n_lcols", "n_rcols"))
def _gather_out(l_datas, l_masks, r_datas, r_masks, lo, cnt, r_order,
                l_valid, *, out_n: int, left_join: bool, n_lcols: int,
                n_rcols: int):
    """Expand match ranges and gather every output column, on device."""
    Lp = cnt.shape[0]
    valid_l = jnp.arange(Lp, dtype=jnp.int32) < l_valid
    if left_join:
        cnt_out = jnp.where(valid_l, jnp.maximum(cnt, 1), 0)
    else:
        cnt_out = jnp.where(valid_l, cnt, 0)
    offs = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(cnt_out).astype(jnp.int32)])
    total = offs[-1]
    pos = jnp.arange(out_n, dtype=jnp.int32)
    # left-row-per-output-position via scatter-max + cummax: each
    # emitting left row marks its start offset with its index and the
    # running max fills the run. O(Lp + out_n) with ONE scatter — the
    # searchsorted formulation cost ~24 binary-search gather passes over
    # the offsets and dominated merge wall time on TPU.
    starts = jnp.where(cnt_out > 0, offs[:-1],
                       jnp.int32(out_n))          # silent rows → dump
    starts = jnp.minimum(starts, jnp.int32(out_n))
    marks = jnp.zeros(out_n + 1, jnp.int32).at[starts].max(
        jnp.arange(Lp, dtype=jnp.int32))
    li = jax.lax.cummax(marks[:out_n])
    within = pos - offs[li]
    matched = within < cnt[li]
    valid = pos < total
    rp = jnp.clip(lo[li] + within, 0, max(r_order.shape[0] - 1, 0))
    ri = r_order[rp]

    out_l, out_r = [], []
    for i in range(n_lcols):
        out_l.append((l_datas[i][li],
                      l_masks[i][li] | ~valid))
    for i in range(n_rcols):
        out_r.append((r_datas[i][ri],
                      r_masks[i][ri] | ~matched | ~valid))
    return tuple(out_l), tuple(out_r)


def _key_arrays(lc: Column, rc: Column, nrl: int, nrr: int):
    """Comparable (l, r) device key pairs in a common dtype, or None.

    Integer/categorical keys compare as int32 (exact); anything float
    compares as the stored f32. Categorical keys with differing domains
    remap the right codes into the left domain on the host (domains are
    small) before shipping.
    """
    if lc.data is None or rc.data is None:
        return None
    if lc.is_categorical != rc.is_categorical:
        return None
    if lc.is_categorical:
        # codes are domain indices → exact as f32 below 2^24; the
        # all-float NaN-fold path is both faster (one sort pass fewer)
        # and avoids a jaxlib CPU-mesh compile segfault observed on the
        # int32+int8 sort combination. Cardinalities at/above 2^24
        # would alias codes — host path instead of silent collisions.
        if max(len(lc.domain or []), len(rc.domain or [])) >= (1 << 24):
            return None
        ld = lc.data.astype(jnp.float32)
        if (lc.domain or []) == (rc.domain or []):
            rd = rc.data.astype(jnp.float32)
        else:
            lut = {lvl: i for i, lvl in enumerate(lc.domain or [])}
            rdom = rc.domain or []
            mp = np.asarray([lut.get(lvl, -1) for lvl in rdom], np.int32)
            codes = np.asarray(rc.data).astype(np.int64)
            na = np.asarray(rc.na_mask)
            remapped = mp[np.clip(codes, 0, max(len(rdom) - 1, 0))] \
                if len(rdom) else np.full(len(codes), -1, np.int32)
            # unseen right levels (-1) must never match: fold into NA.
            # Shard like every other column input — one unsharded
            # operand among sharded ones reproducibly segfaulted the
            # jaxlib CPU-mesh compiler
            rna = na | (remapped < 0)
            shard = mesh_mod.row_sharding()
            rd = mesh_mod.put_sharded(
                np.where(rna, 0, remapped).astype(np.float32), shard)
            return (ld, lc.na_mask, rd,
                    mesh_mod.put_sharded(rna, shard))
        return (ld, lc.na_mask, rd, rc.na_mask)
    l_int = jnp.issubdtype(lc.data.dtype, jnp.integer)
    r_int = jnp.issubdtype(rc.data.dtype, jnp.integer)
    if l_int and r_int:
        return (lc.data.astype(jnp.int32), lc.na_mask,
                rc.data.astype(jnp.int32), rc.na_mask)
    return (lc.data.astype(jnp.float32), lc.na_mask,
            rc.data.astype(jnp.float32), rc.na_mask)


def device_merge(lf: Frame, rf: Frame, key_names: List[str],
                 how: str) -> Optional[Frame]:
    """Multi-key equi-join with the whole pipeline on device; returns the
    joined Frame or None when the inputs need the host path (string/uuid
    columns, right/outer joins, tiny frames)."""
    if how not in ("inner", "left"):
        return None
    if not key_names:
        return None                      # host path raises a clear error
    if max(lf.nrows, rf.nrows) < DEVICE_MERGE_MIN_ROWS:
        return None
    if lf.nrows == 0 or rf.nrows == 0:
        return None
    l_keys, l_nas, r_keys, r_nas = [], [], [], []
    for k in key_names:
        pair = _key_arrays(lf.col(k), rf.col(k), lf.nrows, rf.nrows)
        if pair is None:
            return None
        lk, lna, rk, rna = pair
        l_keys.append(lk)
        l_nas.append(lna)
        r_keys.append(rk)
        r_nas.append(rna)
    l_cols = [lf.col(n) for n in lf.names]
    r_cols = [rf.col(n) for n in rf.names if n not in set(key_names)]
    if any(c.data is None for c in l_cols + r_cols):
        return None                      # string/uuid columns → host

    nk = len(key_names)
    lv = jnp.int32(lf.nrows)
    rv = jnp.int32(rf.nrows)
    lo, cnt, r_order = _match_ranges(tuple(l_keys), tuple(l_nas),
                                     tuple(r_keys), tuple(r_nas), lv, rv,
                                     n_keys=nk)

    left_join = how == "left"
    # ONE scalar crosses the tunnel — fetching the full cnt vector
    # (40MB at 10M rows) through a remote-attached chip costs seconds
    t_left, t_inner = _total_rows(cnt, lv)
    total = int(t_left) if left_join else int(t_inner)
    if total == 0:
        return _empty_like(lf, rf, key_names)
    # Low-cardinality keys make equi-joins quadratic (a 66K x 16K join
    # on a 4-level key is 208M output rows). Materializing that on the
    # device mesh starves XLA's CPU collective rendezvous (40s
    # termination timeout -> hard process abort, the round-4 crash) and
    # would OOM small HBM slices; size the output BEFORE allocating and
    # hand oversized joins to the host path, like BinaryMerge's
    # per-chunk result sizing (water/rapids/BinaryMerge.java).
    out_cells = total * (len(l_cols) + len(r_cols))
    if out_cells * 9 > _merge_out_budget():      # 8B data + 1B mask
        log.warning("device merge result %d rows x %d cols (%.1f GB) "
                    "exceeds device budget - host merge path",
                    total, len(l_cols) + len(r_cols), out_cells * 9 / 1e9)
        return None
    out_n = mesh_mod.padded_rows(total, block=8)

    out_l, out_r = _gather_out(
        tuple(c.data for c in l_cols), tuple(c.na_mask for c in l_cols),
        tuple(c.data for c in r_cols), tuple(c.na_mask for c in r_cols),
        lo, cnt, r_order, lv, out_n=out_n, left_join=left_join,
        n_lcols=len(l_cols), n_rcols=len(r_cols))

    shard = mesh_mod.row_sharding()
    collide = {c.name for c in r_cols if c.name in set(lf.names)}
    new_cols = []
    for c, (d, m) in zip(l_cols, out_l):
        nm = c.name + "_x" if c.name in collide else c.name
        new_cols.append(Column(
            name=nm, type=c.type, data=mesh_mod.put_sharded(d, shard),
            na_mask=mesh_mod.put_sharded(m, shard), nrows=total,
            domain=c.domain))
    for c, (d, m) in zip(r_cols, out_r):
        nm = c.name + "_y" if c.name in collide else c.name
        new_cols.append(Column(
            name=nm, type=c.type, data=mesh_mod.put_sharded(d, shard),
            na_mask=mesh_mod.put_sharded(m, shard), nrows=total,
            domain=c.domain))
    return Frame(new_cols, total)


def _empty_like(lf: Frame, rf: Frame, key_names: List[str]) -> Frame:
    arrays, cats, doms = {}, [], {}
    collide = {n for n in rf.names
               if n not in set(key_names) and n in set(lf.names)}
    for n in lf.names:
        c = lf.col(n)
        nm = n + "_x" if n in collide else n
        if c.is_categorical:
            arrays[nm] = np.zeros(0, np.int32)
            cats.append(nm)
            doms[nm] = c.domain
        else:
            arrays[nm] = np.zeros(0, np.float64)
    for n in rf.names:
        if n in set(key_names):
            continue
        c = rf.col(n)
        nm = n + "_y" if n in collide else n
        if c.is_categorical:
            arrays[nm] = np.zeros(0, np.int32)
            cats.append(nm)
            doms[nm] = c.domain
        else:
            arrays[nm] = np.zeros(0, np.float64)
    return Frame.from_numpy(arrays, categorical=cats, domains=doms)
