"""Pallas TPU kernel layer — knob resolution, fallback policy, telemetry.

The fused tree kernels live in ``ops/pallas/treekernel.py``; this module
is the POLICY layer and deliberately imports neither jax nor the kernels
at module scope, so it stays importable (and testable) where
``jax.experimental.pallas`` does not exist at all — the import-guard
contract: a missing Pallas can only ever mean "XLA path, one logged
fallback", never an ImportError in a training run.

Knob (``H2O3TPU_PALLAS`` env / ``Config.pallas``):

    auto       Pallas on TPU backends, XLA everywhere else (default)
    off        always XLA
    on         force native Pallas (TPU only in practice)
    interpret  force the kernels through the Pallas interpreter — the
               CPU tier-1 parity mode (bit-exact vs the XLA path)

Every fallback decision increments ``pallas_fallbacks_total{reason=}``
and logs ONCE per reason per process (no per-tree spam); every kernel
program instantiation increments ``pallas_kernel_launches_total{kernel=}``
at trace time (compiled programs re-run without touching Python, so the
counter reads as "distinct kernel builds", not per-step executions).
Both flow into each job's flight-recorder capsule via the start→end
counter deltas like any other counter.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

_AVAILABLE: Optional[bool] = None
_LOGGED_REASONS = set()        # single logged fallback per reason/process


def available() -> bool:
    """True when ``jax.experimental.pallas`` imports (cached)."""
    global _AVAILABLE
    if _AVAILABLE is None:
        try:
            import jax.experimental.pallas  # noqa: F401
            _AVAILABLE = True
        except Exception:      # noqa: BLE001 - any import failure = absent
            _AVAILABLE = False
    return _AVAILABLE


def knob_value() -> str:
    """The H2O3TPU_PALLAS knob (env wins over Config default)."""
    env = os.environ.get("H2O3TPU_PALLAS")
    if env:
        return env
    try:
        from h2o3_tpu.core.config import ARGS
        return getattr(ARGS, "pallas", "auto") or "auto"
    except Exception:          # noqa: BLE001 - config must never gate this
        return "auto"


def decide(knob: str, backend: str, data_shards: int,
           avail: bool) -> Tuple[str, Optional[str]]:
    """Pure decision table: (mode, fallback_reason).

    mode is 'off' | 'native' | 'interpret'; reason is None when Pallas
    was selected. ``data_shards`` rides along for the bench stub's
    planner line — the kernels shard over 'data' like the XLA path, so
    shard count never forces a fallback.
    """
    knob = (knob or "auto").strip().lower()
    if knob in ("off", "0", "false", "xla"):
        return "off", "knob_off"
    if not avail:
        return "off", "pallas_unavailable"
    if knob == "interpret":
        return "interpret", None
    if knob in ("on", "native", "1", "force"):
        return "native", None
    if knob == "auto":
        if backend != "tpu":
            return "off", "non_tpu_backend"
        return "native", None
    return "off", "unknown_knob"


def resolve_tree_mode() -> str:
    """Resolve the tree-kernel mode for a fit (counts + logs fallbacks).

    Called once per model fit by the tree builders; the result rides in
    ``TreeParams.pallas`` (a STATIC jit field), so flipping the knob
    mid-process compiles a fresh boosting program instead of silently
    reusing a cached one with the old decision.
    """
    import jax
    mode, reason = decide(knob_value(), jax.default_backend(), 1,
                          available())
    if reason is not None:
        record_fallback(reason)
    return mode


def record_fallback(reason: str) -> None:
    """Count a Pallas→XLA fallback; log once per reason per process."""
    from h2o3_tpu import telemetry
    telemetry.counter("pallas_fallbacks_total", reason=reason).inc()
    if reason not in _LOGGED_REASONS:
        _LOGGED_REASONS.add(reason)
        from h2o3_tpu.utils.log import get_logger
        get_logger("h2o3_tpu.ops.pallas").info(
            "Pallas tree kernels falling back to XLA (%s); further "
            "occurrences counted in pallas_fallbacks_total, not logged",
            reason)


def record_launch(kernel: str) -> None:
    """Count a pallas_call instantiation (trace time)."""
    from h2o3_tpu import telemetry
    telemetry.counter("pallas_kernel_launches_total", kernel=kernel).inc()


def vmem_tile_rows(n_features: int, n_bins: int, n_nodes: int,
                   budget_bytes: int = 8 << 20) -> int:
    """Row extent of a bin-major tile that fits the phase-A working set
    in a VMEM budget: the int8 bins tile, the f32 one-hot (feature, bin)
    indicator, the f32 node⊗stat routing block, and double-counted
    histogram accumulator + output. Pure math (the bench stub's planner
    runs it with no backend); floors to a sublane multiple of 8.
    """
    per_row = (n_features                    # int8 bins lane
               + 4 * n_features * n_bins     # f32 one-hot right
               + 4 * 3 * n_nodes             # f32 left block
               + 64)                         # slack
    fixed = 2 * 4 * 3 * n_nodes * n_features * n_bins
    rows = max((int(budget_bytes) - fixed) // per_row, 8)
    return max(8, (rows // 8) * 8)
