"""Fused Pallas tree kernels: histogram + best-split + partition per level.

The XLA level loop (models/tree.py grow_tree) touches the binned matrix
three times per depth level — one-hot matmul histograms
(ops/histogram.py), the split scan, then ``_level_goleft`` re-reads the
matrix to route rows — with every intermediate round-tripping HBM. This
module fuses the whole per-level inner loop the way the GPU tree-boosting
systems do (Booster arxiv 2011.02022; XGBoost-GPU arxiv 1806.11248):

- single data shard: ONE ``pallas_call`` over a (phase, tile) grid.
  Phase 0 streams bin-major tiles (frame/binning.py tile layout: int8,
  feature-major lanes, NA folded in as bin B-1) through VMEM and
  accumulates the [3L, F·B] histogram in a VMEM scratch on the MXU;
  the phase boundary derives the level histogram (sibling subtraction
  against the parent level), runs the shared split scan
  (ops/split_scan.py — the SAME function the XLA path calls, so the
  two paths are bit-exact by construction), and parks the decisions in
  the kernel's output refs; phase 1 re-streams the tiles and routes
  every row to its child, all without leaving the chip.
- sharded mesh: the same phase bodies split into a per-shard histogram
  kernel, the cross-shard ``psum`` (the MRTask reduce tree,
  water/MRTask.java:891 — a hard barrier no fusion can remove), the
  boundary math, and a per-shard partition kernel.

Numerics contract: with ``interpret=True`` (CPU tier-1) every output is
bit-exact vs the XLA path on the same mesh — f32 accumulation with the
XLA path's exact row-block structure, identical split tie-breaking
(shared code), integer routing. Native TPU runs may pick VMEM-sized
tiles instead (ops/pallas.vmem_tile_rows) and trade the bitwise match
for throughput; the XLA path remains the always-available fallback
behind ``H2O3TPU_PALLAS`` (core/config.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from h2o3_tpu.ops import pallas as pallas_policy
from h2o3_tpu.ops.split_scan import best_splits
from h2o3_tpu.parallel.mesh import DATA_AXIS, shard_map


# --------------------------------------------------------------- tile math


def _tile_geometry(n_rows: int, block_rows: int):
    """(C, nblk, n_pad): the XLA path's exact row-block structure
    (ops/histogram.py _local_histogram) — sharing it is what makes the
    f32 accumulation order, and therefore the histograms, bit-identical
    in interpret mode."""
    C = min(block_rows, n_rows)
    nblk = (n_rows + C - 1) // C
    return C, nblk, nblk * C


def _pad_rows(arr, n_pad: int):
    n = arr.shape[0]
    if n == n_pad:
        return arr
    return jnp.pad(arr, ((0, n_pad - n),) + ((0, 0),) * (arr.ndim - 1))


# ----------------------------------------------------- shared phase bodies


def _hist_block(bins, nid, stats, *, n_nodes_h: int, n_bins: int, d: int):
    """One tile's [3Lh, F·B] partial histogram — VMEM one-hots feeding
    the MXU. Values (not just sums) match ops/histogram._block_hist: the
    one-hot indicators are exact 0/1 and the stats ride untouched, so
    the f32 contraction sees identical operands. At levels d >= 1 only
    LEFT-child rows accumulate, into their PARENT's slot (the sibling-
    subtraction trick of grow_tree, kept inside the kernel)."""
    C, F = bins.shape
    bins = bins.astype(jnp.int32)
    if d > 0:
        even = ((nid % 2) == 0).astype(jnp.float32)      # [C, 1]
        stats = stats * even
        nid = nid >> 1
    feat_off = jax.lax.broadcasted_iota(jnp.int32, (C, F), 1) * n_bins
    fb = bins + feat_off                                 # [C, F] in [0, FB)
    lane = jax.lax.broadcasted_iota(jnp.int32, (C, F * n_bins), 1)
    right = (lane == fb[:, 0:1]).astype(jnp.float32)
    for f in range(1, F):
        right += (lane == fb[:, f:f + 1]).astype(jnp.float32)
    lane3 = jax.lax.broadcasted_iota(jnp.int32, (C, n_nodes_h * 3), 1)
    node_of_k = lane3 // 3
    stat_of_k = lane3 - 3 * node_of_k
    node_hit = (nid == node_of_k).astype(jnp.float32)    # [C, 3Lh]
    # stat broadcast via SELECT (not masked add): a NaN stat lane must
    # not bleed into its siblings' columns the way 0*NaN would
    stat_b = jnp.where(stat_of_k == 0, stats[:, 0:1],
                       jnp.where(stat_of_k == 1, stats[:, 1:2],
                                 stats[:, 2:3]))
    left = node_hit * stat_b                             # [C, 3Lh]
    return jax.lax.dot_general(
        left.T, right, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _level_boundary(lh, prev_hist, cm, nb, is_cat, constraints, lo, hi,
                    knobs, dl, *, d: int, n_nodes: int, n_bins: int,
                    n_features: int):
    """Histogram → split decisions, between the two row passes.

    Line-for-line the XLA level head of grow_tree: reshape the matmul
    accumulator to [Lh, F, B, 3], sibling-subtract against the parent
    level (with the f32 cancellation clamps), then the SHARED split scan
    (ops/split_scan.best_splits) and the split/categorical flags."""
    Lh = max(n_nodes // 2, 1)
    lh4 = lh.reshape(Lh, 3, n_features, n_bins).transpose(0, 2, 3, 1)
    if d == 0:
        hist = lh4
    else:
        rh = prev_hist - lh4
        rh = rh.at[..., 0].set(jnp.maximum(rh[..., 0], 0.0))
        rh = rh.at[..., 2].set(jnp.maximum(rh[..., 2], 0.0))
        hist = jnp.stack([lh4, rh], axis=1).reshape(n_nodes,
                                                    *lh4.shape[1:])
    bg, bf, bt, bnal, blv, brv, leftmask = best_splits(
        hist, nb, cm != 0, min_rows=knobs[0, 0], reg_lambda=knobs[0, 1],
        is_cat=is_cat, constraints=constraints, lo=lo, hi=hi)
    split = bg > knobs[0, 2]
    split = split & (jnp.int32(d) < dl[0, 0])
    if is_cat is not None:
        cs = is_cat[bf] & split
    else:
        cs = jnp.zeros_like(split)
    return hist, bg, bf, bt, bnal, blv, brv, leftmask, split, cs


def _partition_block(bins, nid, bf, bt, bnal, isp, cs, leftmask, *,
                     n_bins: int):
    """Route one tile's rows to their children — gather-free
    ``_level_goleft`` semantics (one-hot selects + a 0/1 matmul for the
    categorical left-set membership). Pure integer/boolean work ⇒
    bit-exact against the XLA routing by construction."""
    C, F = bins.shape
    L = bf.shape[0]
    bins = bins.astype(jnp.int32)
    noh = nid == jax.lax.broadcasted_iota(jnp.int32, (C, L), 1)  # [C, L]
    f_r = jnp.sum(jnp.where(noh, bf[None, :], 0), axis=1,
                  keepdims=True)                                 # [C, 1]
    t_r = jnp.sum(jnp.where(noh, bt[None, :], 0), axis=1)        # [C]
    nal_r = jnp.sum(jnp.where(noh, bnal.astype(jnp.int32)[None, :], 0),
                    axis=1) > 0
    isp_r = jnp.sum(jnp.where(noh, isp.astype(jnp.int32)[None, :], 0),
                    axis=1) > 0
    cs_r = jnp.sum(jnp.where(noh, cs.astype(jnp.int32)[None, :], 0),
                   axis=1) > 0
    fio = jax.lax.broadcasted_iota(jnp.int32, (C, F), 1)
    b_r = jnp.sum(jnp.where(f_r == fio, bins, 0), axis=1)        # [C]
    isna = b_r == (n_bins - 1)
    go_num = b_r <= t_r
    # leftmask[nid, b_r] without a 2D gather: 0/1 matmul over nodes,
    # then a lane select over bins (exact — operands are indicators)
    row_mask = jax.lax.dot_general(
        noh.astype(jnp.float32), leftmask.astype(jnp.float32),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                      # [C, B-1]
    bio = jax.lax.broadcasted_iota(jnp.int32, (C, n_bins - 1), 1)
    inset = jnp.sum(jnp.where(bio == b_r[:, None], row_mask, 0.0),
                    axis=1) > 0.5
    go_split = jnp.where(cs_r, inset, go_num)
    goleft = jnp.where(isp_r, jnp.where(isna, nal_r, go_split), True)
    return 2 * nid + jnp.where(goleft, 0, 1)[:, None]


# --------------------------------------------- single-shard fused kernel


def _fused_kernel(bins_ref, nid_ref, stats_ref, prev_ref, cm_ref, nb_ref,
                  iscat_ref, cons_ref, lo_ref, hi_ref, knobs_ref, dl_ref,
                  hist_ref, bg_ref, bf_ref, bt_ref, bnal_ref, blv_ref,
                  brv_ref, lmask_ref, isp_ref, newnid_ref,
                  acc_ref, cs_ref, *, d: int, n_nodes: int, n_bins: int,
                  n_features: int, nblk: int, has_cats: bool,
                  has_cons: bool):
    phase = pl.program_id(0)
    blk = pl.program_id(1)

    @pl.when((phase == 0) & (blk == 0))
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(phase == 0)
    def _():
        acc_ref[:] += _hist_block(
            bins_ref[:], nid_ref[:], stats_ref[:],
            n_nodes_h=max(n_nodes // 2, 1), n_bins=n_bins, d=d)
        newnid_ref[:] = nid_ref[:]       # placeholder until phase 1

    @pl.when((phase == 1) & (blk == 0))
    def _():
        hist, bg, bf, bt, bnal, blv, brv, lmask, split, cs = \
            _level_boundary(
                acc_ref[:], prev_ref[:] if d > 0 else None, cm_ref[:],
                nb_ref[0], iscat_ref[0] != 0 if has_cats else None,
                cons_ref[0] if has_cons else None, lo_ref[0], hi_ref[0],
                knobs_ref[:], dl_ref[:], d=d, n_nodes=n_nodes,
                n_bins=n_bins, n_features=n_features)
        hist_ref[:] = hist
        bg_ref[0, :] = bg
        bf_ref[0, :] = bf
        bt_ref[0, :] = bt
        bnal_ref[0, :] = bnal.astype(jnp.int32)
        blv_ref[0, :] = blv
        brv_ref[0, :] = brv
        lmask_ref[:] = lmask.astype(jnp.int32)
        isp_ref[0, :] = split.astype(jnp.int32)
        cs_ref[0, :] = cs.astype(jnp.int32)

    @pl.when(phase == 1)
    def _():
        newnid_ref[:] = _partition_block(
            bins_ref[:], nid_ref[:], bf_ref[0, :], bt_ref[0, :],
            bnal_ref[0, :] != 0, isp_ref[0, :] != 0, cs_ref[0, :] != 0,
            lmask_ref[:] != 0, n_bins=n_bins)


def _fused_call(bins, nid, stats, prev, cm2, nb2, iscat, cons, lo2, hi2,
                knobs, dl, *, d, n_nodes, n_bins, block_rows, interpret):
    """The tentpole: hist + split + partition in ONE pallas_call over the
    bin-major tiles — phase 0 accumulates, the boundary decides, phase 1
    re-streams the same tiles and routes."""
    N, F = bins.shape
    C, nblk, n_pad = _tile_geometry(N, block_rows)
    bins_p = _pad_rows(bins, n_pad)
    nid_p = _pad_rows(nid, n_pad).reshape(-1, 1)
    stats_p = _pad_rows(stats, n_pad)
    Lh = max(n_nodes // 2, 1)
    L, B = n_nodes, n_bins
    Lcm = cm2.shape[0]
    Llo = lo2.shape[1]

    pallas_policy.record_launch("tree_fused_level")
    grid = (2, nblk)
    full = lambda *shape: pl.BlockSpec(       # noqa: E731 - spec shorthand
        shape, lambda p, b: (0,) * len(shape))
    tile = lambda *shape: pl.BlockSpec(       # noqa: E731
        shape, lambda p, b: (b,) + (0,) * (len(shape) - 1))
    kern = functools.partial(
        _fused_kernel, d=d, n_nodes=L, n_bins=B, n_features=F, nblk=nblk,
        has_cats=iscat is not None, has_cons=cons is not None)
    outs = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            tile(C, F), tile(C, 1), tile(C, 3),
            full(Lh, F, B, 3), full(Lcm, F), full(1, F),
            full(1, F), full(1, F), full(1, Llo), full(1, Llo),
            full(1, 3), full(1, 1),
        ],
        out_specs=[
            full(L, F, B, 3),
            full(1, L), full(1, L), full(1, L), full(1, L),
            full(1, L), full(1, L), full(L, B - 1), full(1, L),
            tile(C, 1),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((L, F, B, 3), jnp.float32),
            jax.ShapeDtypeStruct((1, L), jnp.float32),
            jax.ShapeDtypeStruct((1, L), jnp.int32),
            jax.ShapeDtypeStruct((1, L), jnp.int32),
            jax.ShapeDtypeStruct((1, L), jnp.int32),
            jax.ShapeDtypeStruct((1, L), jnp.float32),
            jax.ShapeDtypeStruct((1, L), jnp.float32),
            jax.ShapeDtypeStruct((L, B - 1), jnp.int32),
            jax.ShapeDtypeStruct((1, L), jnp.int32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((3 * Lh, F * B), jnp.float32),
            pltpu.VMEM((1, L), jnp.int32),
        ],
        interpret=interpret,
    )(bins_p, nid_p, stats_p,
      prev if prev is not None else jnp.zeros((Lh, F, B, 3), jnp.float32),
      cm2, nb2, iscat if iscat is not None else jnp.zeros((1, F), jnp.int8),
      cons if cons is not None else jnp.zeros((1, F), jnp.int8),
      lo2, hi2, knobs, dl)
    (hist, bg, bf, bt, bnal, blv, brv, lmask, isp, newnid) = outs
    return (hist, bg[0], bf[0], bt[0], bnal[0] != 0, blv[0], brv[0],
            lmask != 0, isp[0] != 0, newnid[:N, 0])


# --------------------------------------------- sharded two-kernel variant


def _hist_kernel(bins_ref, nid_ref, stats_ref, out_ref, acc_ref, *,
                 d: int, n_nodes_h: int, n_bins: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += _hist_block(bins_ref[:], nid_ref[:], stats_ref[:],
                              n_nodes_h=n_nodes_h, n_bins=n_bins, d=d)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


def _hist_call(bins, nid, stats, *, d, n_nodes, n_bins, block_rows,
               interpret):
    """Per-shard histogram kernel → [3Lh, F·B] (caller psums)."""
    N, F = bins.shape
    C, nblk, n_pad = _tile_geometry(N, block_rows)
    Lh = max(n_nodes // 2, 1)
    pallas_policy.record_launch("tree_hist")
    return pl.pallas_call(
        functools.partial(_hist_kernel, d=d, n_nodes_h=Lh, n_bins=n_bins),
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((C, F), lambda i: (i, 0)),
            pl.BlockSpec((C, 1), lambda i: (i, 0)),
            pl.BlockSpec((C, 3), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((3 * Lh, F * n_bins), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((3 * Lh, F * n_bins), jnp.float32),
        scratch_shapes=[pltpu.VMEM((3 * Lh, F * n_bins), jnp.float32)],
        interpret=interpret,
    )(_pad_rows(bins, n_pad), _pad_rows(nid, n_pad).reshape(-1, 1),
      _pad_rows(stats, n_pad))


def _partition_kernel(bins_ref, nid_ref, bf_ref, bt_ref, bnal_ref,
                      isp_ref, cs_ref, lmask_ref, newnid_ref, *,
                      n_bins: int):
    newnid_ref[:] = _partition_block(
        bins_ref[:], nid_ref[:], bf_ref[0], bt_ref[0], bnal_ref[0] != 0,
        isp_ref[0] != 0, cs_ref[0] != 0, lmask_ref[:] != 0, n_bins=n_bins)


def _partition_call(bins, nid, bf, bt, bnal, isp, cs, lmask, *, n_bins,
                    block_rows, interpret):
    """Per-shard split+partition kernel → routed node ids [N]."""
    N, F = bins.shape
    C, nblk, n_pad = _tile_geometry(N, block_rows)
    L = bf.shape[0]
    pallas_policy.record_launch("tree_partition")
    full = lambda *shape: pl.BlockSpec(       # noqa: E731
        shape, lambda i: (0,) * len(shape))
    newnid = pl.pallas_call(
        functools.partial(_partition_kernel, n_bins=n_bins),
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((C, F), lambda i: (i, 0)),
            pl.BlockSpec((C, 1), lambda i: (i, 0)),
            full(1, L), full(1, L), full(1, L), full(1, L), full(1, L),
            full(L, n_bins - 1),
        ],
        out_specs=pl.BlockSpec((C, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
        interpret=interpret,
    )(_pad_rows(bins, n_pad), _pad_rows(nid, n_pad).reshape(-1, 1),
      bf[None, :], bt[None, :], bnal.astype(jnp.int32)[None, :],
      isp.astype(jnp.int32)[None, :], cs.astype(jnp.int32)[None, :],
      lmask.astype(jnp.int32))
    return newnid[:N, 0]


# ----------------------------------------------------------- entry points


def fused_level(bins, nid, stats, prev_hist, col_mask, nb, is_cat,
                constraints, lo, hi, scalars, *, d: int, n_nodes: int,
                n_bins: int, block_rows: int, mesh, interpret: bool):
    """One tree level, fused: returns (hist [L,F,B,3], gain, feat,
    thresh, na_left, left_val, right_val, leftmask, split, new_nid).

    Drop-in for grow_tree's per-level XLA sequence (histogram →
    _best_splits → _level_goleft), with identical semantics: ``stats``
    is the level-invariant [N, 3] {w, w·g, w·h} block, ``prev_hist`` the
    previous level's histogram (None at the root — sibling subtraction
    starts at level 1), and the returned ``split`` already folds in the
    min-split-improvement and traced depth-limit masks. Rows must be
    pre-padded to the mesh (N %% data-shards == 0), as grow_tree's are.

    Native mode caps the tile rows at the VMEM-sized suggestion;
    interpret mode keeps the XLA path's exact block structure so tier-1
    can assert bitwise parity.
    """
    knobs = jnp.stack([scalars.min_rows, scalars.reg_lambda,
                       scalars.msi]).astype(jnp.float32).reshape(1, 3)
    dl = (scalars.depth_limit if scalars.depth_limit is not None
          else jnp.int32(1 << 30))
    dl = jnp.asarray(dl, jnp.int32).reshape(1, 1)
    cm2 = (col_mask if col_mask.ndim == 2
           else col_mask[None, :]).astype(jnp.int8)
    nb2 = jnp.asarray(nb, jnp.int32)[None, :]
    iscat = None if is_cat is None else is_cat.astype(jnp.int8)[None, :]
    cons = (None if constraints is None
            else jnp.asarray(constraints, jnp.int8)[None, :])
    lo2 = jnp.asarray(lo, jnp.float32)[None, :]
    hi2 = jnp.asarray(hi, jnp.float32)[None, :]
    if not interpret:
        block_rows = min(block_rows, pallas_policy.vmem_tile_rows(
            bins.shape[1], n_bins, n_nodes))
    F = bins.shape[1]

    ndata = mesh.shape[DATA_AXIS]
    if ndata == 1:
        return _fused_call(bins, nid, stats, prev_hist, cm2, nb2, iscat,
                           cons, lo2, hi2, knobs, dl, d=d,
                           n_nodes=n_nodes, n_bins=n_bins,
                           block_rows=block_rows, interpret=interpret)

    # sharded: per-shard hist kernel, psum barrier, shared boundary
    # math, per-shard partition kernel — same bodies, same numbers
    has_cats = iscat is not None
    has_cons = cons is not None
    Lh = max(n_nodes // 2, 1)
    prev = (prev_hist if prev_hist is not None
            else jnp.zeros((Lh, F, n_bins, 3), jnp.float32))
    iscat_in = iscat if has_cats else jnp.zeros((1, F), jnp.int8)
    cons_in = cons if has_cons else jnp.zeros((1, F), jnp.int8)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)) + (P(),) * 9,
        out_specs=(P(),) * 9 + (P(DATA_AXIS),), check_vma=False)
    def _task(bins_l, nid_l, stats_l, prev, cm2, nb2, iscat_a, cons_a,
              lo2, hi2, knobs, dl):
        lh = _hist_call(bins_l, nid_l, stats_l, d=d, n_nodes=n_nodes,
                        n_bins=n_bins, block_rows=block_rows,
                        interpret=interpret)
        lh = jax.lax.psum(lh, DATA_AXIS)
        hist, bg, bf, bt, bnal, blv, brv, lmask, split, cs = \
            _level_boundary(
                lh, prev if d > 0 else None, cm2, nb2[0],
                iscat_a[0] != 0 if has_cats else None,
                cons_a[0] if has_cons else None, lo2[0], hi2[0], knobs,
                dl, d=d, n_nodes=n_nodes, n_bins=n_bins, n_features=F)
        newnid_l = _partition_call(bins_l, nid_l, bf, bt, bnal, split,
                                   cs, lmask, n_bins=n_bins,
                                   block_rows=block_rows,
                                   interpret=interpret)
        return (hist, bg, bf, bt, bnal, blv, brv, lmask, split, newnid_l)

    return _task(bins, nid, stats, prev, cm2, nb2, iscat_in, cons_in,
                 lo2, hi2, knobs, dl)


def xla_level(bins, nid, w, g, h, prev_hist, col_mask, nb, is_cat,
              constraints, lo, hi, scalars, *, d: int, n_nodes: int,
              n_bins: int, block_rows: int, mesh):
    """Reference composition — grow_tree's per-level XLA sequence as one
    callable, for the interpret-parity tests and the bench `treekernel`
    leg. Same return tuple as fused_level."""
    from h2o3_tpu.models.tree import _level_goleft, _pack_leftmask
    from h2o3_tpu.ops.histogram import histogram
    L, B = n_nodes, n_bins
    if d == 0 or prev_hist is None:
        hist = histogram(bins, nid, w, g, h, n_nodes=L, n_bins=B,
                         mesh=mesh, block_rows=block_rows)
    else:
        even = (nid % 2 == 0).astype(jnp.float32)
        lh = histogram(bins, nid >> 1, w * even, g, h, n_nodes=L // 2,
                       n_bins=B, mesh=mesh, block_rows=block_rows)
        rh = prev_hist - lh
        rh = rh.at[..., 0].set(jnp.maximum(rh[..., 0], 0.0))
        rh = rh.at[..., 2].set(jnp.maximum(rh[..., 2], 0.0))
        hist = jnp.stack([lh, rh], axis=1).reshape(L, *lh.shape[1:])
    bg, bf, bt, bnal, blv, brv, leftmask = best_splits(
        hist, nb, col_mask, min_rows=scalars.min_rows,
        reg_lambda=scalars.reg_lambda, is_cat=is_cat,
        constraints=constraints, lo=lo, hi=hi)
    split = bg > scalars.msi
    if scalars.depth_limit is not None:
        split = split & (jnp.int32(d) < scalars.depth_limit)
    feat_d = jnp.where(split, bf, 0)
    thresh_d = jnp.where(split, bt, B)
    nal_d = jnp.where(split, bnal, False)
    if is_cat is not None:
        cs = is_cat[bf] & split
        W = max(1, (B - 1 + 31) // 32)
        words = jnp.where(cs[:, None], _pack_leftmask(leftmask, W), 0)
    else:
        cs = jnp.zeros_like(split)
        words = jnp.zeros((L, 1), jnp.uint32)
    newnid = _level_goleft(feat_d, thresh_d, nal_d, split, cs, words,
                           nid, bins, B)
    return (hist, bg, bf, bt, bnal, blv, brv, leftmask, split, newnid)
