"""Distributed (node, feature, bin) histogram — THE hot loop of tree building.

Reference: hex/tree/DHistogram.java:585-674 ``updateHisto`` accumulates
{w, wY, wYY} per (leaf, col, bin) with scalar adds inside an MRTask;
reduce = elementwise histogram add up the thread/node trees
(hex/tree/ScoreBuildHistogram2.java:62).

TPU-native: scatter-add is MXU-hostile, so the accumulation is recast as
two matmuls per row-block (SURVEY §7 "hard parts" #1):

    left  [3L, C] = (one_hot(node) ⊗ [w, g, h])ᵀ     (C = block rows)
    right [C, FB] = one_hot(feature-bin)             (0/1, bf16)
    hist += left @ right                             → [3L, FB]

The contraction over C rows runs on the systolic array; ``lax.scan`` over
row blocks bounds memory (the F/J chunk loop analogue); ``psum`` over the
'data' mesh axis is the cross-node reduce tree (water/MRTask.java:891).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from h2o3_tpu.parallel.mesh import shard_map
from jax.sharding import PartitionSpec as P

from h2o3_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

# A standalone Pallas histogram kernel exists (ops/pallas_histogram) but
# measures ~2x slower than the XLA formulation on v5e (the one-hot
# construction is VPU-bound either way, and XLA fuses it into the matmul
# at larger row blocks than fit VMEM). Opt in with H2O3_TPU_PALLAS_HIST=1
# — read ONCE at import: histogram() only runs at trace time inside
# jit-cached programs, so a mid-process toggle could never take effect
# anyway. The FUSED tree kernels (ops/pallas/treekernel.py, knob
# H2O3TPU_PALLAS) supersede it for the grow_tree level loop by folding
# the split scan and row partition into the same pass — this module
# stays the always-available XLA fallback and the non-tree histogram
# entry point.
import os as _os
_USE_PALLAS_FLAG = _os.environ.get("H2O3_TPU_PALLAS_HIST") == "1"


def _block_hist(bins_blk, nid_blk, stats_blk, n_nodes: int, n_bins: int,
                precision=None):
    """One row-block's [3L, FB] partial histogram via MXU matmul."""
    C, F = bins_blk.shape
    # right: 0/1 indicator of (feature, bin) per row — exact in bf16
    onehot_fb = (bins_blk[:, :, None] ==
                 jnp.arange(n_bins, dtype=jnp.int32)[None, None, :])
    right = onehot_fb.reshape(C, F * n_bins).astype(jnp.float32)
    # left: stats routed to the row's node. f32 on both sides: the stats
    # side would lose ~0.4% in bf16, corrupting gains; XLA's bf16x3 pass
    # keeps the MXU busy for f32 contractions. ``precision=HIGHEST``
    # (small-problem mode) trades MXU rate for true-f32 accumulation —
    # the reference pyunits assert metric equality at 1e-5 relative,
    # which bf16x3 residue can miss (pyunit_weights_gbm, 1.9e-5 off).
    node_oh = (nid_blk[:, None] ==
               jnp.arange(n_nodes, dtype=jnp.int32)[None, :]).astype(jnp.float32)
    left = (node_oh[:, :, None] * stats_blk[:, None, :])  # [C, L, 3]
    left = left.reshape(C, n_nodes * 3)
    return jax.lax.dot_general(
        left.T, right, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision)


def _local_histogram(bins, nid, stats, n_nodes: int, n_bins: int,
                     block_rows: int, precision=None):
    """Scan row blocks of one shard, accumulating the [L,F,B,3] histogram."""
    N, F = bins.shape
    C = min(block_rows, N)
    nblk = (N + C - 1) // C
    Npad = nblk * C
    if Npad != N:
        bins = jnp.pad(bins, ((0, Npad - N), (0, 0)))
        nid = jnp.pad(nid, (0, Npad - N))
        stats = jnp.pad(stats, ((0, Npad - N), (0, 0)))  # w=0 ⇒ no effect? see below
        # padding rows carry zero stats so they contribute nothing
    bins_b = bins.reshape(nblk, C, F)
    nid_b = nid.reshape(nblk, C)
    stats_b = stats.reshape(nblk, C, 3)

    def step(acc, xs):
        b, n, s = xs
        return acc + _block_hist(b, n, s, n_nodes, n_bins,
                                 precision=precision), None

    init = jnp.zeros((n_nodes * 3, F * n_bins), jnp.float32)
    acc, _ = jax.lax.scan(step, init, (bins_b, nid_b, stats_b))
    # [3L, FB] -> [L, F, B, 3]
    return acc.reshape(n_nodes, 3, F, n_bins).transpose(0, 2, 3, 1)


def histogram(bins, nid, w, g, h, *, n_nodes: int, n_bins: int,
              mesh, block_rows: int = 16384, precision=None):
    """All-reduced histogram [n_nodes, F, n_bins, {w,g,h}] over the mesh.

    Inputs are row-sharded over 'data'; output is replicated. Padding rows
    must have w == 0; stats accumulate {w, w·g, w·h} exactly as the
    reference accumulates {w, wY, wYY}.
    """
    stats = jnp.stack([w, w * g, w * h], axis=1).astype(jnp.float32)
    ndata = mesh.shape[DATA_AXIS]
    N = bins.shape[0]
    if N % ndata != 0:
        pad = ndata - N % ndata
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        nid = jnp.pad(nid, (0, pad))
        stats = jnp.pad(stats, ((0, pad), (0, 0)))

    use_pallas = jax.default_backend() == "tpu" and _USE_PALLAS_FLAG

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(), check_vma=False)
    def _task(bins_l, nid_l, stats_l):
        if use_pallas:
            from h2o3_tpu.ops.pallas_histogram import pallas_local_histogram
            hist = pallas_local_histogram(bins_l, nid_l, stats_l,
                                          n_nodes, n_bins,
                                          block_rows=min(block_rows, 512))
        else:
            hist = _local_histogram(bins_l, nid_l, stats_l, n_nodes, n_bins,
                                    block_rows, precision=precision)
        # psum over 'data' only: inputs are replicated over 'model', so
        # including it would scale every stat by the model-axis size
        return jax.lax.psum(hist, DATA_AXIS)

    return _task(bins, nid, stats)
