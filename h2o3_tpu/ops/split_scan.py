"""Vectorized best-split scan over a level's (node, feature, bin) histogram.

Reference: hex/tree/DTree.java:619-697 ``findBestSplitPoint`` — cumulative
{w, wY, wYY} over bins, gain per threshold, NA-direction choice, and the
sorted-prefix categorical subset scan.

This is the single split-scan implementation shared by BOTH tree
backends: ``models/tree.py`` calls it from the XLA level loop, and
``ops/pallas/treekernel.py`` evaluates the very same function at the
fused kernel's histogram→partition boundary. One body ⇒ the two paths
are bit-exact by construction (the interpret-mode parity contract of
tests/test_tree_kernels.py) and can never drift apart.
"""

from __future__ import annotations

import jax.numpy as jnp


def best_splits(hist, nb, col_mask, *, min_rows, reg_lambda,
                is_cat=None, constraints=None, lo=None, hi=None):
    """Vectorized DTree.findBestSplitPoint over all nodes of a level.

    hist: [L, F, B, 3] of {w, g, h}; col_mask [F] (per-tree sampling) or
    [L, F] (per-node mtries, DRF). With ``constraints`` ([F] in
    {-1,0,+1}) and per-node value bounds lo/hi ([L]), splits on
    constrained features must order their (bound-clipped) child Newton
    values per the constraint direction — the monotone-constraints
    contract of the reference GBM (hex/tree/DHistogram constraints +
    hex/tree/Constraints).

    Categorical features (``is_cat`` [F] bool; pass None for an
    all-numeric scan): bins are re-ordered PER NODE by their Newton value
    -g/(h+λ) and the threshold scan runs over that order, so the best
    "prefix" is the best category SUBSET — the static-shape formulation
    of the reference's bitset splits (hex/tree/DTree.java:619-697
    findBestSplitPoint sorts by prediction then scans). Returns per-node
    best (gain, feat, thresh, na_left, left_val, right_val, leftmask)
    where leftmask [L, B-1] marks the ORIGINAL bin ids going left.
    """
    lam = reg_lambda
    B = hist.shape[2]
    w, g, h = hist[..., 0], hist[..., 1], hist[..., 2]
    wv = w[:, :, : B - 1]
    gv = g[:, :, : B - 1]
    hv = h[:, :, : B - 1]
    order = None
    if is_cat is not None:
        # per-(node, feature) bin order: Newton value ascending for cats,
        # natural bin order for numerics (identity keeps the exact
        # numeric semantics). Empty bins key to 0 and sort mid-sequence;
        # their left/right membership carries no weight either way.
        # empty bins key to +inf so they sort AFTER every populated bin:
        # the t <= nb-2 threshold-validity mask then stays correct in
        # sorted space (populated bins occupy a prefix of it)
        val = jnp.where(wv > 0, -gv / (hv + lam + 1e-10), jnp.inf)
        pos = jnp.arange(B - 1, dtype=jnp.float32)
        key = jnp.where(is_cat[None, :, None], val, pos[None, None, :])
        order = jnp.argsort(key, axis=2, stable=True)
        wv = jnp.take_along_axis(wv, order, axis=2)
        gv = jnp.take_along_axis(gv, order, axis=2)
        hv = jnp.take_along_axis(hv, order, axis=2)
    # cumulative over (possibly re-ordered) value bins; NA bin is B-1
    cw = jnp.cumsum(wv, axis=2)
    cg = jnp.cumsum(gv, axis=2)
    ch = jnp.cumsum(hv, axis=2)
    naw, nag, nah = w[:, :, B - 1], g[:, :, B - 1], h[:, :, B - 1]
    tw = cw[:, :, -1] + naw
    tg = cg[:, :, -1] + nag
    th = ch[:, :, -1] + nah
    if lo is None:
        lo = jnp.full((hist.shape[0],), -jnp.inf, jnp.float32)
        hi = jnp.full((hist.shape[0],), jnp.inf, jnp.float32)

    def gain(gl, hl, gr, hr):
        return (gl * gl / (hl + lam) + gr * gr / (hr + lam)
                - tg[:, :, None] ** 2 / (th[:, :, None] + lam))

    def child_vals(gl, hl, gr, hr):
        lv = jnp.clip(-gl / (hl + lam), lo[:, None, None], hi[:, None, None])
        rv = jnp.clip(-gr / (hr + lam), lo[:, None, None], hi[:, None, None])
        return lv, rv

    def masked_gain(wl, gl, hl):
        wr = tw[:, :, None] - wl
        gr = tg[:, :, None] - gl
        hr = th[:, :, None] - hl
        ok = (wl >= min_rows) & (wr >= min_rows)
        lv, rv = child_vals(gl, hl, gr, hr)
        if constraints is not None:
            c = constraints[None, :, None].astype(jnp.float32)
            ok = ok & (c * (rv - lv) >= 0)
        return jnp.where(ok, gain(gl, hl, gr, hr), -jnp.inf), lv, rv

    g_nar, lv_nar, rv_nar = masked_gain(cw, cg, ch)         # NA → right
    g_nal, lv_nal, rv_nal = masked_gain(
        cw + naw[:, :, None], cg + nag[:, :, None],
        ch + nah[:, :, None])                               # NA → left
    # threshold validity: t <= nb[f]-2 (splitting at last real bin is void)
    t_ids = jnp.arange(B - 1, dtype=jnp.int32)
    valid_t = t_ids[None, :] <= (nb[:, None] - 2)           # [F, B-1]
    cm = col_mask if col_mask.ndim == 2 else col_mask[None, :]   # [L|1, F]
    mask = valid_t[None, :, :] & cm[:, :, None]
    g_nar = jnp.where(mask, g_nar, -jnp.inf)
    g_nal = jnp.where(mask, g_nal, -jnp.inf)

    stacked = jnp.stack([g_nar, g_nal], axis=-1)            # [L, F, B-1, 2]
    L = stacked.shape[0]
    flat = stacked.reshape(L, -1)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    na_left = (best % 2).astype(bool)
    best_t = ((best // 2) % (B - 1)).astype(jnp.int32)
    best_f = (best // (2 * (B - 1))).astype(jnp.int32)
    lvals = jnp.stack([lv_nar, lv_nal], axis=-1).reshape(L, -1)
    rvals = jnp.stack([rv_nar, rv_nal], axis=-1).reshape(L, -1)
    best_lv = jnp.take_along_axis(lvals, best[:, None], axis=1)[:, 0]
    best_rv = jnp.take_along_axis(rvals, best[:, None], axis=1)[:, 0]
    if order is not None:
        # original-bin-id membership of the winning prefix: position of
        # bin b within the winning feature's order <= t  ⇔  b goes left
        order_win = jnp.take_along_axis(
            order, best_f[:, None, None], axis=1)[:, 0]     # [L, B-1]
        ranks = jnp.argsort(order_win, axis=1)              # inverse perm
        leftmask = ranks <= best_t[:, None]
    else:
        leftmask = (jnp.arange(B - 1, dtype=jnp.int32)[None, :]
                    <= best_t[:, None])
    return best_gain, best_f, best_t, na_left, best_lv, best_rv, leftmask
