"""Distributed Gram matrix — X'WX / X'Wz via blocked matmuls + psum.

Reference: hex/gram/Gram.java:15 — GLM's IRLS inner loop accumulates the
weighted Gram over an MRTask (GLMIterationTask, hex/glm/GLMTask.java) and
solves by Cholesky with collinear-column dropping (Gram.java:229,452).
TPU-native: the accumulation is a single einsum contraction over the
row-sharded data axis; `lax.scan` over row blocks bounds the [C, P]
design-block memory; `psum` replaces the reduce tree.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from h2o3_tpu.parallel.mesh import shard_map
from jax.sharding import PartitionSpec as P

from h2o3_tpu.parallel.mesh import DATA_AXIS


def _local_gram(X, wz, block_rows: int):
    """Accumulate [P, P] X'WX, [P] X'Wz, scalars over one shard.

    wz: [N, 2] = (w, w*z) stacked. Returns (XtWX, XtWz, wsum).
    """
    N, Pdim = X.shape
    C = min(block_rows, N)
    nblk = (N + C - 1) // C
    Npad = nblk * C
    if Npad != N:
        X = jnp.pad(X, ((0, Npad - N), (0, 0)))
        wz = jnp.pad(wz, ((0, Npad - N), (0, 0)))
    Xb = X.reshape(nblk, C, Pdim)
    wzb = wz.reshape(nblk, C, 2)

    def step(acc, xs):
        xtx, xtz, ws = acc
        Xc, wzc = xs
        wX = Xc * wzc[:, 0:1]
        xtx = xtx + jax.lax.dot_general(
            wX.T, Xc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        xtz = xtz + Xc.T @ wzc[:, 1]   # X'(w·z)
        ws = ws + jnp.sum(wzc[:, 0])
        return (xtx, xtz, ws), None

    init = (jnp.zeros((Pdim, Pdim), jnp.float32),
            jnp.zeros((Pdim,), jnp.float32), jnp.float32(0.0))
    (xtx, xtz, ws), _ = jax.lax.scan(step, init, (Xb, wzb))
    return xtx, xtz, ws


def gram(X, w, z, *, mesh, block_rows: int = 8192):
    """All-reduced (X'WX, X'Wz, sum w) over the mesh.

    X [N, P] row-sharded design matrix (with intercept column appended by
    the caller); w weights (0 on padding rows); z working response.
    """
    wz = jnp.stack([w, w * z], axis=1)
    ndata = mesh.shape[DATA_AXIS]
    N = X.shape[0]
    if N % ndata != 0:
        pad = ndata - N % ndata
        X = jnp.pad(X, ((0, pad), (0, 0)))
        wz = jnp.pad(wz, ((0, pad), (0, 0)))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P(), P()), check_vma=False)
    def _task(X_l, wz_l):
        xtx, xtz, ws = _local_gram(X_l, wz_l, block_rows)
        return (jax.lax.psum(xtx, DATA_AXIS),
                jax.lax.psum(xtz, DATA_AXIS),
                jax.lax.psum(ws, DATA_AXIS))

    return _task(X, wz)


def gram_model_sharded(X, w, z, *, mesh, block_rows: int = 8192):
    """Model-axis-sharded Gram: X columns sharded over 'model', rows over
    'data'; the X'X cross-block products stream around the model axis as
    a ppermute ring (the collective-matmul recipe — each device holds one
    column block, receives its neighbours' blocks one hop at a time, and
    never materializes the full-width matrix).

    This is the TP-like axis SURVEY §2.4 item 6 reserves for wide one-hot
    GLM feature spaces (the reference's sharded-Gram analogue of
    hex/gram/Gram.java over very wide DataInfo expansions).

    Returns (XtWX [P, P] sharded over columns, XtWz [P], wsum) — all
    psum-reduced over 'data'.
    """
    from h2o3_tpu.parallel.mesh import MODEL_AXIS
    nmodel = mesh.shape[MODEL_AXIS]
    ndata = mesh.shape[DATA_AXIS]
    N, Pdim = X.shape
    P0 = Pdim
    if nmodel == 1:
        return gram(X, w, z, mesh=mesh, block_rows=block_rows)
    if Pdim % nmodel != 0:
        padc = nmodel - Pdim % nmodel
        X = jnp.pad(X, ((0, 0), (0, padc)))
        Pdim += padc
    wz = jnp.stack([w, w * z], axis=1)
    if N % ndata != 0:
        pad = ndata - N % ndata
        X = jnp.pad(X, ((0, pad), (0, 0)))
        wz = jnp.pad(wz, ((0, pad), (0, 0)))
    Pm = Pdim // nmodel

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS)),
        out_specs=(P(None, MODEL_AXIS), P(MODEL_AXIS), P()),
        check_vma=False)
    def _task(X_l, wz_l):
        # X_l: [N/d, Pm] — this rank's column block; ring-stream the
        # other ranks' blocks to fill the [P, Pm] column slab of X'WX
        my = jax.lax.axis_index(MODEL_AXIS)
        wX = X_l * wz_l[:, 0:1]
        out = jnp.zeros((Pdim, Pm), jnp.float32)
        Y = X_l
        src = my
        perm = [(i, (i - 1) % nmodel) for i in range(nmodel)]
        for _hop in range(nmodel):
            # block (src, my) of the Gram: Y holds rank `src`'s columns
            blk = jax.lax.dot_general(
                Y.T, wX, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)      # [Pm, Pm]
            out = jax.lax.dynamic_update_slice(out, blk, (src * Pm, 0))
            Y = jax.lax.ppermute(Y, MODEL_AXIS, perm)
            src = (src + 1) % nmodel
        xtz = X_l.T @ wz_l[:, 1]
        ws = jnp.sum(wz_l[:, 0])
        return (jax.lax.psum(out, DATA_AXIS),
                jax.lax.psum(xtz, DATA_AXIS),
                jax.lax.psum(ws, (DATA_AXIS, MODEL_AXIS)) / nmodel)

    xtx, xtz, ws = _task(X, wz)
    # drop the nmodel-alignment padding: callers solve [P0, P0] normal
    # equations and a zero row/col would make them singular
    return xtx[:P0, :P0], xtz[:P0], ws
