"""Device-side sort / join kernels for the Rapids munging surface.

Reference: water/rapids/RadixOrder.java + BinaryMerge.java — the
distributed MSD-radix order and the chunk-wise binary merge join. The
TPU-native collapse: XLA's sort IS the distributed sort primitive (jit
over row-sharded inputs lets SPMD partitioning insert the collectives),
so the controller never materializes the column data; it only touches
O(#matches) index metadata for joins. Host numpy remains the tiny-frame
path — sub-64K-row pyunit frames would pay more in compile+dispatch
than they save.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.column import Column
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.parallel import mesh as mesh_mod

# below this many rows the host path wins (compile + device dispatch
# dominate); above it the device path also avoids a full host copy
DEVICE_SORT_MIN_ROWS = 65536


@partial(jax.jit, static_argnames=("n_keys", "valid_n"))
def _lexsort_device(keys, nas, *, n_keys: int, valid_n: int):
    """Stable ascending lexsort over ``keys`` (last key = primary is NOT
    the convention here — keys[0] is the PRIMARY key). NAs sort last
    (reference sort NA handling); padding rows sort after everything.
    Returns the [Npad] int32 permutation (valid rows first)."""
    N = keys[0].shape[0]
    order = jnp.arange(N, dtype=jnp.int32)
    # iterate minor→major keys with a stable argsort each round
    for i in range(n_keys - 1, -1, -1):
        k = keys[i]
        k = jnp.where(nas[i], jnp.inf, k)            # NA → last
        kk = k[order]
        order = order[jnp.argsort(kk, stable=True)]
    # padding rows (index >= valid_n) must land at the very end while
    # keeping the relative order of valid rows: one more stable pass
    order = order[jnp.argsort((order >= valid_n).astype(jnp.int32),
                              stable=True)]
    return order


@partial(jax.jit, static_argnames=("n_cols",))
def _gather_cols(datas, nas, order, *, n_cols: int):
    out_d, out_m = [], []
    for i in range(n_cols):
        out_d.append(datas[i][order])
        out_m.append(nas[i][order])
    return tuple(out_d), tuple(out_m)


def _f32_safe(c) -> bool:
    """True when the column's values survive a float32 cast EXACTLY, so
    the device compare order matches the host float64 path: float
    columns are already stored f32; integer columns qualify only within
    the f32-exact range ±2^24 (an int32 ID column of ~1e9 would collapse
    nearby keys into spurious ties/matches)."""
    if c.data is None:
        return False
    if jnp.issubdtype(c.data.dtype, jnp.floating):
        return True
    if c.data.dtype in (jnp.int8, jnp.int16, jnp.uint8, jnp.uint16):
        return True                              # always f32-exact
    from h2o3_tpu.frame.rollups import rollups
    try:
        stats = rollups(c)
        return max(abs(float(stats.get("min", 0))),
                   abs(float(stats.get("max", 0)))) < 2 ** 24
    except Exception:
        return False


def device_sort(frame: Frame, key_names: List[str],
                ascending: List[bool]) -> Optional[Frame]:
    """Sort ``frame`` by key columns entirely on device; returns the new
    Frame or None when the frame is not device-sortable (string columns
    ride along on the host, so their presence forces the host path)."""
    if frame.nrows < DEVICE_SORT_MIN_ROWS:
        return None
    cols = [frame.col(n) for n in frame.names]
    if any(c.data is None for c in cols):
        return None                       # string/uuid columns → host
    if any(not _f32_safe(frame.col(n)) for n in key_names):
        return None                       # f32-unsafe keys → host path
    keys, nas = [], []
    for n, asc in zip(key_names, ascending):
        c = frame.col(n)
        v = c.data.astype(jnp.float32)
        keys.append(v if asc else -v)
        nas.append(c.na_mask)
    order = _lexsort_device(tuple(keys), tuple(nas),
                            n_keys=len(keys), valid_n=frame.nrows)
    datas, masks = _gather_cols(tuple(c.data for c in cols),
                                tuple(c.na_mask for c in cols), order,
                                n_cols=len(cols))
    shard = mesh_mod.row_sharding()
    new_cols = []
    for c, d, m in zip(cols, datas, masks):
        new_cols.append(Column(
            name=c.name, type=c.type,
            data=mesh_mod.put_sharded(d, shard),
            na_mask=mesh_mod.put_sharded(m, shard),
            nrows=frame.nrows, domain=c.domain))
    return Frame(new_cols, frame.nrows)


@partial(jax.jit, static_argnames=("l_valid", "r_valid"))
def _join_core(l_key, r_key, *, l_valid: int, r_valid: int):
    """The whole device half of the join as ONE program: sort the right
    keys, binary-search every left key (BinaryMerge's per-key search,
    batched). One compiled call = one tunnel round trip; the previous
    eager formulation paid ~100 ms per op through a remote-attached
    chip."""
    lk = jnp.where(jnp.isnan(l_key[:l_valid]), jnp.inf, l_key[:l_valid])
    rk = jnp.where(jnp.isnan(r_key[:r_valid]), jnp.inf, r_key[:r_valid])
    r_order = jnp.argsort(rk, stable=True)
    r_sorted = rk[r_order]
    lo = jnp.searchsorted(r_sorted, lk, side="left")
    hi = jnp.searchsorted(r_sorted, lk, side="right")
    return r_order.astype(jnp.int32), lo.astype(jnp.int32), \
        hi.astype(jnp.int32), jnp.isinf(lk)


def device_join_index(l_key: jax.Array, r_key: jax.Array,
                      l_valid: int, r_valid: int):
    """Single-key equi-join indices with the heavy work on device.

    Returns host arrays (l_idx, r_idx) of matching row pairs (inner
    join core; callers add unmatched rows for left/right/outer). The
    device does the O(N log N) sort + binary searches; the host only
    expands the per-row match ranges (O(#matches) memcpy).
    """
    r_order, lo, hi, nan_l = (np.asarray(a) for a in _join_core(
        l_key, r_key, l_valid=l_valid, r_valid=r_valid))
    lo_h, hi_h = lo, hi
    cnt = np.where(nan_l, 0, hi_h - lo_h)
    l_idx = np.repeat(np.arange(l_valid), cnt)
    # per-left-row runs lo..hi expanded into sorted-right positions
    starts = np.repeat(lo_h, cnt)
    within = np.arange(cnt.sum()) - np.repeat(
        np.concatenate([[0], np.cumsum(cnt)[:-1]]), cnt)
    r_pos = starts + within
    r_idx = r_order[r_pos]
    return l_idx, r_idx
