"""Optimization primitives: ADMM (L1 quadratic), L-BFGS, Cholesky solve.

Reference: hex/optimization/ADMM.java (L1Solver for the IRLS proximal
subproblem) and hex/optimization/L_BFGS.java (two-loop recursion +
backtracking line search) — both driven from hex/glm/GLM.java:1451,2056.
Here: the quadratic ADMM runs entirely on device around one Cholesky
factorization; L-BFGS keeps its (small) history on host and calls a
jitted value-and-gradient (the gradient evaluation is the distributed
part — one Gram-style pass per iteration).
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def soft_threshold(x, k):
    return jnp.sign(x) * jnp.maximum(jnp.abs(x) - k, 0.0)


def admm_l1_quadratic(A, q, l1: float, penalize_mask,
                      rho: float = 1.0, iters: int = 200,
                      tol: float = 1e-6):
    """min_b ½ b'Ab - q'b + l1·|b∘mask|₁  via ADMM (ADMM.java:L1Solver).

    A must be PSD; one Cholesky of (A + ρI), then ``iters`` cheap steps
    inside lax.while_loop. penalize_mask: 1.0 for penalized coords, 0.0
    for intercept.
    """
    P = A.shape[0]
    L = jax.scipy.linalg.cho_factor(A + rho * jnp.eye(P, dtype=A.dtype))

    def body(state):
        b, z, u, it, _ = state
        b_new = jax.scipy.linalg.cho_solve(L, q + rho * (z - u))
        z_new = soft_threshold(b_new + u, l1 / rho * penalize_mask)
        u_new = u + b_new - z_new
        delta = jnp.max(jnp.abs(z_new - z))
        return (b_new, z_new, u_new, it + 1, delta)

    def cond(state):
        _, _, _, it, delta = state
        return (it < iters) & (delta > tol)

    z0 = jnp.zeros((P,), A.dtype)
    state = (z0, z0, z0, jnp.int32(0), jnp.float32(1.0))
    b, z, u, _, _ = jax.lax.while_loop(cond, body, state)
    return z  # the sparse iterate


def cholesky_solve_regularized(XtWX, XtWz, l2: float, penalize_mask,
                               ridge_boost: float = 1e-6):
    """Solve (XtWX + l2·diag(mask)) b = XtWz, with a tiny ridge for rank
    safety (the reference drops collinear columns, Gram.java:229; a
    minimal ridge is the static-shape equivalent)."""
    P = XtWX.shape[0]
    reg = l2 * penalize_mask + ridge_boost
    A = XtWX + jnp.diag(reg)
    L = jax.scipy.linalg.cho_factor(A)
    return jax.scipy.linalg.cho_solve(L, XtWz)


def lbfgs(value_and_grad: Callable, x0: np.ndarray, max_iter: int = 100,
          m: int = 10, gtol: float = 1e-5, ls_max: int = 20) -> Tuple[np.ndarray, float, int]:
    """Host-orchestrated L-BFGS (L_BFGS.java) with Armijo backtracking.

    ``value_and_grad(x) -> (f, g)`` runs jitted on device; history math is
    tiny and stays on host.
    """
    x = np.asarray(x0, np.float64)
    f, g = value_and_grad(x)
    f, g = float(f), np.asarray(g, np.float64)
    S, Y, rhos = [], [], []
    n_iter = 0
    for n_iter in range(1, max_iter + 1):
        if np.max(np.abs(g)) < gtol:
            break
        # two-loop recursion
        qd = g.copy()
        alphas = []
        for s, yv, r in zip(reversed(S), reversed(Y), reversed(rhos)):
            a = r * s.dot(qd)
            alphas.append(a)
            qd -= a * yv
        if Y:
            gamma = S[-1].dot(Y[-1]) / max(Y[-1].dot(Y[-1]), 1e-12)
            qd *= gamma
        for s, yv, r, a in zip(S, Y, rhos, reversed(alphas)):
            b = r * yv.dot(qd)
            qd += (a - b) * s
        d = -qd
        gd = g.dot(d)
        if gd > 0:  # not a descent direction; reset
            d, gd = -g, -g.dot(g)
            S, Y, rhos = [], [], []
        # backtracking
        step = 1.0
        for _ in range(ls_max):
            xn = x + step * d
            fn, gn = value_and_grad(xn)
            fn = float(fn)
            if np.isfinite(fn) and fn <= f + 1e-4 * step * gd:
                break
            step *= 0.5
        else:
            break
        gn = np.asarray(gn, np.float64)
        s, yv = xn - x, gn - g
        sy = s.dot(yv)
        if sy > 1e-10:
            S.append(s); Y.append(yv); rhos.append(1.0 / sy)
            if len(S) > m:
                S.pop(0); Y.pop(0); rhos.pop(0)
        x, f, g = xn, fn, gn
    return x, f, n_iter


def coordinate_descent_quadratic(A, q, l1, l2, penalize_mask,
                                 lower=None, upper=None,
                                 sweeps: int = 100):
    """Cyclic coordinate descent on the elastic-net quadratic

        min_b  1/2 b'Ab - q'b + l1*||m.b||_1 + l2/2*||m.b||^2
        s.t.   lower <= b <= upper          (optional box)

    — the glmnet-style inner loop of the reference's COD solver
    (hex/glm/GLM.java:1495 fitCOD) and, with a box, its
    beta_constraints / non_negative projected update (hex/optimization/
    ADMM L1Solver bounds). A is the P x P normalized Gram, so the
    sequential coordinate sweep is tiny host-side-shape work that still
    compiles to one fori_loop program on device.
    """
    P = A.shape[0]
    Ad = jnp.maximum(jnp.diag(A) + l2 * penalize_mask, 1e-12)
    lo = jnp.full((P,), -jnp.inf) if lower is None else jnp.asarray(lower)
    hi = jnp.full((P,), jnp.inf) if upper is None else jnp.asarray(upper)

    def one_coord(j, b):
        # partial residual gradient for coordinate j
        g = q[j] - A[j] @ b + A[j, j] * b[j]
        t = l1 * penalize_mask[j]
        bj = jnp.sign(g) * jnp.maximum(jnp.abs(g) - t, 0.0) / Ad[j]
        bj = jnp.clip(bj, lo[j], hi[j])
        return b.at[j].set(bj)

    def one_sweep(_, b):
        return jax.lax.fori_loop(0, P, one_coord, b)

    b0 = jnp.zeros((P,), A.dtype)
    return jax.lax.fori_loop(0, sweeps, one_sweep, b0)
