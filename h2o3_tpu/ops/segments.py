"""Segment (per-node) stat sums via the same matmul trick as histogram.

Reference: leaf-value passes like GammaPass (hex/tree/gbm/GBM.java:520)
accumulate per-leaf numerator/denominator with an MRTask. Here: one
one-hot matmul per row block, psum over the data axis.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from h2o3_tpu.parallel.mesh import shard_map
from jax.sharding import PartitionSpec as P

from h2o3_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from h2o3_tpu.telemetry import observed_jit


def _local_segment_sum(nid, vals, n_nodes: int, block_rows: int,
                       precision=None):
    N = nid.shape[0]
    K = vals.shape[1]
    C = min(block_rows, N)
    nblk = (N + C - 1) // C
    Npad = nblk * C
    if Npad != N:
        nid = jnp.pad(nid, (0, Npad - N))
        vals = jnp.pad(vals, ((0, Npad - N), (0, 0)))
    nid_b = nid.reshape(nblk, C)
    vals_b = vals.reshape(nblk, C, K)

    def step(acc, xs):
        n, v = xs
        oh = (n[:, None] == jnp.arange(n_nodes, dtype=jnp.int32)[None, :])
        part = jax.lax.dot_general(
            oh.astype(jnp.float32).T, v.astype(jnp.float32),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            precision=precision)
        return acc + part, None

    init = jnp.zeros((n_nodes, K), jnp.float32)
    acc, _ = jax.lax.scan(step, init, (nid_b, vals_b))
    return acc


def segment_sum(nid, vals, *, n_nodes: int, mesh, block_rows: int = 16384,
                precision=None):
    """All-reduced per-node sums: vals [N, K] → [n_nodes, K].

    Rows with all-zero vals (padding) contribute nothing; nid must be in
    [0, n_nodes).

    n_nodes is bucketed up to the next power of two internally (result
    sliced back): every distinct group count would otherwise compile its
    own XLA program — a group-by sweep over many cardinalities (the
    munging pyunits) pays 20-40s of TPU compile per distinct count.
    """
    want = n_nodes
    if n_nodes > 1:
        n_nodes = 1 << (n_nodes - 1).bit_length()
    ndata = mesh.shape[DATA_AXIS]
    N = nid.shape[0]
    if N % ndata != 0:
        pad = ndata - N % ndata
        nid = jnp.pad(nid, (0, pad))
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
    out = _segment_sum_jit(nid, vals, n_nodes=n_nodes,
                           block_rows=block_rows, mesh=mesh,
                           precision=precision)
    return out if want == n_nodes else out[:want]


@observed_jit("ops.segment_sum")
@functools.partial(jax.jit, static_argnames=("n_nodes", "block_rows",
                                             "mesh", "precision"))
def _segment_sum_jit(nid, vals, *, n_nodes, block_rows, mesh, precision):
    # module-level jit: eager callers (rapids group-by sweeps) hit the
    # trace cache across calls — a per-call closure would re-trace and
    # re-lower the shard_map every time
    task = functools.partial(_local_segment_sum, n_nodes=n_nodes,
                             block_rows=block_rows, precision=precision)

    def _body(nid_l, vals_l):
        return jax.lax.psum(task(nid_l, vals_l), DATA_AXIS)

    return shard_map(_body, mesh=mesh,
                     in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
                     out_specs=P(), check_vma=False)(nid, vals)
