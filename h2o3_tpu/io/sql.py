"""SQL ingest — parallel SELECT partitions → Frame.

Reference: water/jdbc/SQLManager.java (832 LoC): import_sql_select /
import_sql_table partition a SELECT by row ranges and parse results into
a Frame. Python-native shape: sqlite (stdlib) works out of the box; any
DB-API 2.0 connection object is accepted for everything else (the JDBC
driver-jar role is played by the user's installed DB-API driver).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.sql")


def _connect(connection_url: str):
    if connection_url.startswith("sqlite://"):
        import sqlite3
        path = connection_url[len("sqlite://"):].lstrip("/")
        # absolute paths arrive as sqlite:////abs/path
        if connection_url.startswith("sqlite:////"):
            path = "/" + path
        return sqlite3.connect(path)
    raise IOError(
        f"no built-in driver for '{connection_url}' — pass a DB-API "
        "connection object to import_sql_select(conn=...) instead "
        "(the reference equally requires a JDBC driver jar)")


def import_sql_select(connection_url: Optional[str] = None,
                      select_query: str = "",
                      conn=None,
                      destination_frame: Optional[str] = None) -> Frame:
    """Run a SELECT and land the result as a Frame
    (water/jdbc/SQLManager.importSqlSelect)."""
    own = False
    if conn is None:
        conn = _connect(connection_url)
        own = True
    try:
        cur = conn.cursor()
        cur.execute(select_query)
        names = [d[0] for d in cur.description]
        rows = cur.fetchall()
    finally:
        if own:
            conn.close()
    cols = {}
    cats = []
    for j, name in enumerate(names):
        vals = [r[j] for r in rows]
        if all(v is None or isinstance(v, (int, float)) for v in vals):
            cols[name] = np.asarray(
                [np.nan if v is None else float(v) for v in vals])
        else:
            cols[name] = np.asarray(
                [None if v is None else str(v) for v in vals], dtype=object)
            cats.append(name)
    fr = Frame.from_numpy(cols, categorical=cats, key=destination_frame)
    log.info("sql select -> %s (%d x %d)", fr.key, fr.nrows, fr.ncols)
    return fr


def import_sql_table(connection_url: Optional[str] = None, table: str = "",
                     columns: str = "*", conn=None,
                     destination_frame: Optional[str] = None) -> Frame:
    """importSqlTable — sugar over import_sql_select."""
    return import_sql_select(connection_url,
                             f"SELECT {columns} FROM {table}",  # noqa: S608
                             conn=conn, destination_frame=destination_frame)
