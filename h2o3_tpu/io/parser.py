"""Ingest — distributed parse reimagined for a TPU host.

Reference call stack (SURVEY §3.2): ImportFiles → ParseSetup.guessSetup
(sample chunks, guess separator/types/header, water/parser/ParseSetup.java)
→ ParseDataset.forkParseDataset (MultiFileParseTask MRTask tokenizing
chunks on their home nodes, water/parser/ParseDataset.java:127,253) with
cloud-wide categorical interning (ParseDataset.java:356-440).

Here: files are tokenized on the host (pandas' C reader in chunks — the
per-byte CsvParser hot loop, water/parser/CsvParser.java, delegated to a
native tokenizer), types are guessed from a sample exactly like
guessSetup, categorical domains are interned globally, and columns are
shipped once to device HBM, dtype-narrowed and row-sharded. Multi-file
globs concatenate. Parquet via pyarrow covers the h2o-parsers modules.
"""

from __future__ import annotations

import glob as _glob
import os
import re as _re
from typing import Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.parse")

_UUID_RX = _re.compile(
    r"^[0-9a-fA-F]{8}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-"
    r"[0-9a-fA-F]{4}-[0-9a-fA-F]{12}$")


def parse_setup(path: str, nrows_sample: int = 1000,
                header: Optional[bool] = None) -> dict:
    """Schema guess on a sample (ParseSetup.guessSetup).

    CSV guesses from a pandas sample; non-CSV formats (xlsx, parquet,
    ARFF, SVMLight) guess by running their real parser and discarding
    the frame — the reference likewise runs format-specific setup on
    sample chunks (water/parser/ParseSetup.java)."""
    import pandas as pd
    from h2o3_tpu.io import chunking
    expanded = chunking.expand_paths(path)
    if expanded and os.path.exists(expanded[0]):
        path = expanded[0]       # globs/dirs: guess from the first file
    if path.endswith((".parquet", ".pq")):
        # schema only — no data read (multi-GB files must not be parsed
        # twice just to report types). pyarrow.types predicates, not
        # string equality: DataType.__eq__ against a str is always False,
        # so the old ("string", "large_string") comparison never matched
        import pyarrow as pa
        import pyarrow.parquet as pq
        schema = pq.ParquetFile(path).schema_arrow

        def _arrow_setup_type(t) -> str:
            if (pa.types.is_dictionary(t) or pa.types.is_string(t)
                    or pa.types.is_large_string(t) or pa.types.is_binary(t)
                    or pa.types.is_boolean(t)):
                # bools ingest as two-level categoricals (io/formats.py)
                return "categorical"
            if pa.types.is_timestamp(t) or pa.types.is_date(t):
                return "time"
            return "numeric"

        types = {f.name: _arrow_setup_type(f.type) for f in schema}
        return {"columns": list(types), "types": types, "separator": ",",
                "header": True}
    if path.endswith((".xlsx", ".arff", ".svm", ".svmlight")):
        # host text/spreadsheet formats (small by nature): run the real
        # parser and discard — the reference likewise runs format-specific
        # setup on sample chunks (water/parser/ParseSetup.java)
        from h2o3_tpu.core.kv import DKV
        fr = import_file(path)
        cols = list(fr.names)
        types = {n: ("categorical" if fr.col(n).is_categorical else
                     "string" if fr.col(n).type == "string" else "numeric")
                 for n in cols}
        DKV.remove(fr.key)
        return {"columns": cols, "types": types, "separator": ",",
                "header": True}
    # the client's check_header hint wins over sniffing: python-object
    # uploads are all-string QUOTE_ALL CSVs whose header is
    # indistinguishable from data (h2o.py:835 sends check_header=1)
    has_header = guess_header(path) if header is None else bool(header)
    sample = pd.read_csv(path, nrows=nrows_sample,
                         header=0 if has_header else None)
    if not has_header:
        sample.columns = [f"C{i + 1}" for i in range(sample.shape[1])]
    types = {}
    for c in sample.columns:
        # pandas >= 3.0 infers text columns as 'str' dtype, not object
        if sample[c].dtype == object or \
                pd.api.types.is_string_dtype(sample[c].dtype):
            types[c] = "categorical"
        else:
            types[c] = "numeric"
    return {"columns": list(sample.columns), "types": types,
            "separator": ",", "header": has_header}


def _is_num_token(t: str) -> bool:
    try:
        float(t)
        return True
    except ValueError:
        return False


def guess_header(path: str) -> bool:
    """ParseSetup header guess (water/parser/CsvParser.java guess logic):
    a header exists when the first row is all-non-numeric while a later
    row has at least one numeric field."""
    import gzip
    if not path.endswith((".csv", ".csv.gz")):
        return True          # containers (zip/parquet) sniff elsewhere
    op = gzip.open if path.endswith(".gz") else open
    try:
        with op(path, "rt", errors="replace") as f:
            first = f.readline().strip().split(",")
            second = f.readline().strip().split(",")
    except OSError:
        return True
    if not second or second == [""]:
        return True

    def _unq(t: str) -> str:
        # quotes are field escaping, not content: a fully-quoted CSV
        # (h2o-py python-object uploads use QUOTE_ALL) must sniff
        # "42.4" as numeric or the header joins the data and every
        # column collapses to categorical
        t = t.strip()
        if len(t) >= 2 and t[0] == '"' and t[-1] == '"':
            return t[1:-1]
        return t
    first_numeric = any(_is_num_token(_unq(t)) for t in first if t != "")
    second_numeric = any(_is_num_token(_unq(t)) for t in second if t != "")
    return (not first_numeric) and second_numeric


def import_file(path: str, destination_frame: Optional[str] = None,
                col_types: Optional[Dict[str, str]] = None,
                header: Optional[bool] = None, lazy: bool = False,
                na_strings=None):
    """h2o.import_file analogue (h2o-py/h2o/h2o.py:414).

    Accepts a file path, glob, or directory; CSV(.gz/.zip) and Parquet.

    ``lazy=True`` registers a FileBackedFrame stub (the water/fvec
    FileVec role): no bytes are parsed until the key is first fetched
    from the DKV; under memory pressure the Cleaner evicts unmutated
    file-backed frames back to their stub instead of writing spill npz.
    """
    if lazy:
        from h2o3_tpu.core.kv import DKV, make_key
        from h2o3_tpu.io.lazy import FileBackedFrame, sniff_meta
        if os.path.isdir(path):        # same expansion as the eager path
            lp = sorted(os.path.join(path, f) for f in os.listdir(path))
        elif any(ch in path for ch in "*?["):
            lp = sorted(_glob.glob(path))
        else:
            lp = [path]
        if not lp or not all(os.path.exists(f) for f in lp):
            raise FileNotFoundError(path)
        names, nrows, nbytes = (sniff_meta(lp[0], header=header)
                                if len(lp) == 1
                                else (None, None,
                                      sum(os.path.getsize(f) for f in lp)))
        key = destination_frame or make_key("frame")
        stub = FileBackedFrame(key, path, lp, names, nrows, nbytes,
                               {"col_types": col_types, "header": header,
                                "na_strings": na_strings})
        DKV.put(key, stub)
        log.info("registered lazy frame %s -> %s (unparsed, %.1f MB on "
                 "disk)", key, path, (nbytes or 0) / 1e6)
        return stub
    import contextlib
    import time as _time
    from h2o3_tpu import telemetry
    durability = None
    if os.environ.get("H2O3TPU_DATA_DURABILITY", "off") != "off":
        from h2o3_tpu.core import durability
    t0 = _time.time()
    with telemetry.span("parse.import", path=str(path)):
        # durability: hold registration until the lineage stamp below,
        # so one registry entry (with replayable provenance) publishes
        # per ingest instead of an anonymous one being re-homed
        with (durability.suspended() if durability is not None
              else contextlib.nullcontext()):
            fr = _import_file_eager(path, destination_frame, col_types,
                                    header, na_strings)
    telemetry.histogram("parse_seconds").observe(_time.time() - t0)
    _ingest_counters(path, fr)
    # provenance for the Cleaner's cheap eviction path: an unmutated
    # file-backed frame can drop straight back to its stub —
    # na_strings included, or rehydrate reparses without NA mapping
    fr._source_paths = [path] if not isinstance(path, list) else path
    fr._source_kwargs = {"col_types": col_types, "header": header,
                         "na_strings": na_strings}
    if durability is not None:
        # formal ingest lineage: paths + parse plan + format digest —
        # the deterministic re-materialization contract (ISSUE 18)
        durability.record_source(
            fr, fr._source_paths, fr._source_kwargs,
            parse_plan={"format": os.path.splitext(
                str(fr._source_paths[0]))[1].lstrip(".") or "csv",
                "nfiles": len(fr._source_paths)})
        durability.on_frame_put(fr)
    return fr


def _ingest_counters(path, fr) -> None:
    """ingest_bytes_total{format} / ingest_rows_total for the eager
    import path (the chunk-parallel streamer and the Parquet row-group
    reader count their own — parse_parquet self-reports, so the
    single-file parquet branch is skipped here)."""
    from h2o3_tpu import telemetry
    from h2o3_tpu.io import chunking
    expanded = chunking.expand_paths(path)
    if len(expanded) == 1 and \
            chunking.classify_format(expanded[0]) == "parquet":
        return
    try:
        for p in expanded:
            telemetry.counter(
                "ingest_bytes_total",
                format=chunking.classify_format(p)).inc(os.path.getsize(p))
    except OSError:
        pass
    telemetry.counter("ingest_rows_total").inc(fr.nrows)


def _import_file_eager(path: str, destination_frame: Optional[str] = None,
                       col_types: Optional[Dict[str, str]] = None,
                       header: Optional[bool] = None,
                       na_strings=None) -> Frame:
    paths: List[str] = []
    if os.path.isdir(path):
        paths = sorted(os.path.join(path, f) for f in os.listdir(path))
    elif any(ch in path for ch in "*?["):
        paths = sorted(_glob.glob(path))
    else:
        paths = [path]
    if not paths:
        raise FileNotFoundError(path)
    from h2o3_tpu import telemetry
    telemetry.counter("parse_files_total").inc(len(paths))
    try:
        telemetry.counter("parse_bytes_total").inc(
            sum(os.path.getsize(f) for f in paths))
    except OSError:
        pass

    # SVMLight / ARFF (water/parser/{SVMLightParser,ARFFParser} roles)
    if all(f.endswith((".svm", ".svmlight")) for f in paths):
        from h2o3_tpu.io.formats import parse_svmlight
        text = "\n".join(open(f).read() for f in paths)
        return parse_svmlight(text, key=destination_frame)
    if len(paths) == 1 and paths[0].endswith(".arff"):
        from h2o3_tpu.io.formats import parse_arff
        return parse_arff(open(paths[0]).read(), key=destination_frame)
    if len(paths) == 1 and paths[0].endswith((".xlsx", ".xls")):
        if paths[0].endswith(".xls"):
            raise ValueError(
                "legacy BIFF .xls is not supported in this build "
                "(no xlrd); save as .xlsx or .csv")
        from h2o3_tpu.io.formats import parse_xlsx
        fr = parse_xlsx(paths[0], key=destination_frame)
        log.info("parsed %s (xlsx) -> %s (%d x %d)", path, fr.key,
                 fr.nrows, fr.ncols)
        return fr

    # columnar containers: Arrow-native ingest, no pandas detour
    # (h2o-parsers/{parquet,orc,avro} roles)
    if len(paths) == 1:
        from h2o3_tpu.io import formats as _fmt
        kind = None
        if paths[0].endswith((".parquet", ".pq")):
            fr = _fmt.parse_parquet(paths[0], key=destination_frame)
            kind = "parquet"
        elif paths[0].endswith(".orc"):
            fr = _fmt.parse_orc(paths[0], key=destination_frame)
            kind = "orc"
        elif paths[0].endswith(".avro"):
            fr = _fmt.parse_avro(paths[0], key=destination_frame)
            kind = "avro"
        if kind:
            log.info("parsed %s (%s/arrow) -> %s (%d x %d)", path, kind,
                     fr.key, fr.nrows, fr.ncols)
            return fr

    # CSV goes through the native multithreaded tokenizer
    # (h2o3_tpu/native/csv_parser.cpp — the water/parser CsvParser role);
    # zip containers, MULTI-file parquet globs, unknown extensions and
    # any native-parse failure fall back to pandas (single columnar
    # files returned above via the Arrow branch).
    if header is None and paths[0].endswith((".csv", ".csv.gz")):
        # only plain text csv: zips/parquet sniff via their own readers
        header = guess_header(paths[0])
    if all(f.endswith((".csv", ".csv.gz")) for f in paths):
        with telemetry.span("parse.csv_native", files=len(paths)):
            parsed = _parse_csv_native(
                paths, col_types,
                header=True if header is None else header,
                na_strings=na_strings)
        if parsed is not None:
            cols, cats, domains = parsed
            # UUID detection (water/fvec C16Chunk / Vec.T_UUID): a
            # "categorical" whose levels are all uuid-shaped and nearly
            # unique is re-typed as a host-side uuid column
            uuid_cols = []
            forced = set(col_types or ())
            for name in list(cats):
                if name in forced:       # explicit user type wins
                    continue
                dom = domains.get(name) or []
                n_ = len(cols[name])
                if dom and len(dom) > max(16, 0.8 * n_) and \
                        all(_UUID_RX.match(v or "") for v in dom[:64]):
                    lut = np.array(dom, dtype=object)
                    codes = np.asarray(cols[name])
                    vals = np.where(codes >= 0, lut[np.maximum(codes, 0)],
                                    None)
                    cols[name] = vals.astype(object)
                    cats.remove(name)
                    domains.pop(name, None)
                    uuid_cols.append(name)
            str_cols = [c for c, t in (col_types or {}).items()
                        if t == "string" and c in cols
                        and np.asarray(cols[c]).dtype == object]
            fr = Frame.from_numpy(cols, categorical=cats, domains=domains,
                                  strings=str_cols, uuids=uuid_cols,
                                  key=destination_frame)
            log.info("parsed %s (native) -> %s (%d x %d)", path, fr.key,
                     fr.nrows, fr.ncols)
            return fr

    import pandas as pd

    def _na_kw(f):
        """read_csv na_values for this file: positional na_strings map
        to int labels (headerless) or the file's own header names —
        keying by the client's renamed columns would silently no-op."""
        if not na_strings:
            return {}
        if header is False:
            if isinstance(na_strings, dict):
                # headerless columns are ints at read time; the C1..Cn
                # rename happens after — translate, else pandas
                # silently ignores the unknown name keys
                vals = {}
                for k, lst in na_strings.items():
                    m = _re.match(r"^C(\d+)$", str(k))
                    if m and lst:
                        vals[int(m.group(1)) - 1] = list(lst)
                return {"na_values": vals} if vals else {}
            vals = {i: list(lst) for i, lst in enumerate(na_strings)
                    if lst}
            return {"na_values": vals} if vals else {}
        if isinstance(na_strings, dict):
            return {"na_values": na_strings}
        try:
            hdr_names = list(pd.read_csv(f, nrows=0).columns)
        except Exception:
            return {}
        vals = _na_by_name(na_strings, hdr_names)
        return {"na_values": vals} if vals else {}

    frames = []
    for f in paths:
        if f.endswith((".parquet", ".pq")):
            frames.append(pd.read_parquet(f))
        elif header is False:
            df_ = pd.read_csv(f, header=None, **_na_kw(f))
            df_.columns = [f"C{i + 1}" for i in range(df_.shape[1])]
            frames.append(df_)
        else:
            frames.append(pd.read_csv(f, **_na_kw(f)))
    df = pd.concat(frames, ignore_index=True) if len(frames) > 1 else frames[0]
    if col_types:
        for c, t in col_types.items():
            if t in ("enum", "categorical") and c in df.columns:
                df[c] = df[c].astype(str)
            elif t in ("numeric", "real", "int") and c in df.columns:
                df[c] = pd.to_numeric(df[c], errors="coerce")
    fr = Frame.from_pandas(df, key=destination_frame)
    log.info("parsed %s -> %s (%d x %d)", path, fr.key, fr.nrows, fr.ncols)
    return fr


def _na_by_name(na_strings, names_in_order: List[str]) -> Dict[str, List[str]]:
    """Normalize na_strings — a name-keyed dict OR a positional
    list-of-lists in file column order (the ParseSetup naStrings wire
    shape, which stays correct even when the client renames columns at
    parse) — to a dict keyed by the PARSED column names."""
    if not na_strings:
        return {}
    if isinstance(na_strings, dict):
        return {k: list(v) for k, v in na_strings.items() if v}
    out = {}
    for i, lst in enumerate(na_strings):
        if lst and i < len(names_in_order):
            out[names_in_order[i]] = list(lst)
    return out


def _parse_csv_native(paths: List[str],
                      col_types: Optional[Dict[str, str]],
                      header: bool = True,
                      na_strings=None):
    """Multi-file native CSV parse; returns (cols, categorical names) or
    None to fall back. Gzip members are decompressed into the buffer
    (the tokenizer parses bytes, like the reference's ZipUtil front)."""
    from h2o3_tpu.native import parse_csv_bytes
    import gzip
    all_cols: Dict[str, List[np.ndarray]] = {}
    all_doms: Dict[str, List[List[str]]] = {}
    for f in paths:
        try:
            if f.endswith(".gz"):
                data = gzip.open(f, "rb").read()
            else:
                data = open(f, "rb").read()
        except OSError:
            return None
        res = parse_csv_bytes(data, header=header, decode=False)
        if res is None:
            return None
        cols, domains = res
        for name, arr in cols.items():
            all_cols.setdefault(name, []).append(arr)
        for name, dom in domains.items():
            all_doms.setdefault(name, []).append(dom)

    # consistency across files: every file must agree on each column's
    # type (all-categorical or all-numeric) and supply every column —
    # type drift is pandas-concat territory, fall back
    nfiles = len(paths)
    for name, parts in all_cols.items():
        if len(parts) != nfiles:
            return None
        ndoms = len(all_doms.get(name, []))
        if ndoms not in (0, nfiles):
            return None

    merged: Dict[str, np.ndarray] = {}
    domains: Dict[str, List[str]] = {}
    for name, parts in all_cols.items():
        if name in all_doms:
            # multi-file categorical: unify domains and renumber codes
            # (the ParseDataset cloud-wide domain-unification role)
            doms = all_doms[name]
            global_dom = sorted(set().union(*[set(d) for d in doms]))
            lut = {lvl: i for i, lvl in enumerate(global_dom)}
            out_parts = []
            for codes, dom in zip(parts, doms):
                remap = np.asarray([lut[lvl] for lvl in dom] or [0],
                                   dtype=np.int32)
                c = np.where(codes >= 0, remap[np.maximum(codes, 0)], -1)
                out_parts.append(c.astype(np.int32))
            merged[name] = (out_parts[0] if len(out_parts) == 1
                            else np.concatenate(out_parts))
            domains[name] = global_dom
        else:
            merged[name] = parts[0] if len(parts) == 1 else np.concatenate(parts)

    # na_strings apply at parse, BEFORE type coercion and before quoted
    # "" becomes a string token (water/parser/ParseSetup naStrings):
    # matching levels of a sniffed-categorical column become NA (level
    # dropped, codes renumbered); a column left all-numeric afterwards
    # reverts to numeric exactly as the reference's post-NA inference
    # would have typed it.
    for c, nas in _na_by_name(na_strings, list(merged)).items():
        if c not in merged or not nas:
            continue
        nas_set = set(nas)
        if c in domains:
            dom = domains[c]
            keep = [lvl for lvl in dom if lvl not in nas_set]
            if len(keep) != len(dom):
                lut = {lvl: i for i, lvl in enumerate(keep)}
                remap = np.asarray([lut.get(lvl, -1) for lvl in dom] or [-1],
                                   dtype=np.int32)
                codes = merged[c]
                merged[c] = np.where(codes >= 0,
                                     remap[np.maximum(codes, 0)],
                                     -1).astype(np.int32)
                domains[c] = keep
                forced = (col_types or {}).get(c)
                if forced not in ("enum", "categorical", "string") and \
                        all(_is_num_token(lvl) for lvl in keep):
                    lutv = np.asarray([float(lvl) for lvl in keep] or [0.0])
                    codes = merged[c]
                    merged[c] = np.where(codes >= 0,
                                         lutv[np.maximum(codes, 0)], np.nan)
                    domains.pop(c)
        else:
            # numeric column: na tokens that parse numeric were already
            # folded into values — null them back out by VALUE. Known
            # divergence from the reference's token-level match
            # (na_strings=["1"] also nulls cells written "1.0"): the
            # raw tokens are gone after the native tokenizer, and
            # value-match is what "-999 means missing" users intend.
            vals = merged[c]
            for s in nas_set:
                try:
                    vals = np.where(vals == float(s), np.nan, vals)
                except ValueError:
                    pass
            merged[c] = vals

    # honor explicit client types (POST /3/ParseSetup column_types)
    for c, t in (col_types or {}).items():
        if c not in merged:
            continue
        if t in ("enum", "categorical") and c not in domains:
            vals = merged[c]
            import pandas as pd
            strs = np.asarray(
                [None if (isinstance(v, float) and np.isnan(v)) else str(v)
                 for v in vals], dtype=object)
            codes, uniq = pd.factorize(strs, sort=True)
            merged[c] = codes.astype(np.int32)
            domains[c] = [str(u) for u in uniq]
        elif t == "string" and c in domains:
            # client forced a string column the sniffer typed enum
            # (H2OFrame column_types={"D": "string"} — pyunit_isna)
            dom = domains.pop(c)
            lut = np.asarray([str(s) for s in dom], dtype=object)
            codes = merged[c]
            merged[c] = np.asarray(
                [lut[k] if k >= 0 else None for k in codes], dtype=object)
        elif t in ("numeric", "real", "int") and c in domains:
            dom = np.asarray(domains.pop(c))

            def _tonum(s):
                try:
                    return float(s)
                except (TypeError, ValueError):
                    return np.nan
            lut = np.asarray([_tonum(s) for s in dom])
            codes = merged[c]
            merged[c] = np.where(codes >= 0,
                                 lut[np.maximum(codes, 0)]
                                 if len(lut) else np.nan, np.nan)
    return merged, sorted(domains), domains


def export_file(frame: Frame, path: str, force: bool = False,
                sep: str = ",") -> str:
    """Write a Frame as CSV (h2o.export_file → water/api ExportHandler;
    persist drivers resolve the target scheme)."""
    import io as _io
    import os
    from h2o3_tpu.io.persist import persist_manager
    if not force and persist_manager.exists(path):
        raise IOError(f"{path} exists (use force=True)")
    buf = _io.StringIO()
    frame.to_pandas().to_csv(buf, index=False, sep=sep)
    persist_manager.write(path, buf.getvalue().encode())
    log.info("exported %s -> %s", frame.key, path)
    return path


def parse_raw(text: str, destination_frame: Optional[str] = None) -> Frame:
    """Parse CSV text directly (upload path)."""
    import io
    import pandas as pd
    return Frame.from_pandas(pd.read_csv(io.StringIO(text)),
                             key=destination_frame)


def upload_numpy(arrays: Dict[str, np.ndarray],
                 categorical: Sequence[str] = (),
                 destination_frame: Optional[str] = None) -> Frame:
    return Frame.from_numpy(arrays, categorical=categorical,
                            key=destination_frame)
