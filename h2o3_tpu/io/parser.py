"""Ingest — distributed parse reimagined for a TPU host.

Reference call stack (SURVEY §3.2): ImportFiles → ParseSetup.guessSetup
(sample chunks, guess separator/types/header, water/parser/ParseSetup.java)
→ ParseDataset.forkParseDataset (MultiFileParseTask MRTask tokenizing
chunks on their home nodes, water/parser/ParseDataset.java:127,253) with
cloud-wide categorical interning (ParseDataset.java:356-440).

Here: files are tokenized on the host (pandas' C reader in chunks — the
per-byte CsvParser hot loop, water/parser/CsvParser.java, delegated to a
native tokenizer), types are guessed from a sample exactly like
guessSetup, categorical domains are interned globally, and columns are
shipped once to device HBM, dtype-narrowed and row-sharded. Multi-file
globs concatenate. Parquet via pyarrow covers the h2o-parsers modules.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.parse")


def parse_setup(path: str, nrows_sample: int = 1000) -> dict:
    """Schema guess on a sample (ParseSetup.guessSetup)."""
    import pandas as pd
    sample = pd.read_csv(path, nrows=nrows_sample)
    types = {}
    for c in sample.columns:
        if sample[c].dtype == object:
            types[c] = "categorical"
        else:
            types[c] = "numeric"
    return {"columns": list(sample.columns), "types": types,
            "separator": ",", "header": True}


def import_file(path: str, destination_frame: Optional[str] = None,
                col_types: Optional[Dict[str, str]] = None) -> Frame:
    """h2o.import_file analogue (h2o-py/h2o/h2o.py:414).

    Accepts a file path, glob, or directory; CSV(.gz/.zip) and Parquet.
    """
    paths: List[str] = []
    if os.path.isdir(path):
        paths = sorted(os.path.join(path, f) for f in os.listdir(path))
    elif any(ch in path for ch in "*?["):
        paths = sorted(_glob.glob(path))
    else:
        paths = [path]
    if not paths:
        raise FileNotFoundError(path)

    import pandas as pd
    frames = []
    for f in paths:
        if f.endswith((".parquet", ".pq")):
            frames.append(pd.read_parquet(f))
        else:
            frames.append(pd.read_csv(f))
    df = pd.concat(frames, ignore_index=True) if len(frames) > 1 else frames[0]
    if col_types:
        for c, t in col_types.items():
            if t in ("enum", "categorical") and c in df.columns:
                df[c] = df[c].astype(str)
            elif t in ("numeric", "real", "int") and c in df.columns:
                df[c] = pd.to_numeric(df[c], errors="coerce")
    fr = Frame.from_pandas(df, key=destination_frame)
    log.info("parsed %s -> %s (%d x %d)", path, fr.key, fr.nrows, fr.ncols)
    return fr


def parse_raw(text: str, destination_frame: Optional[str] = None) -> Frame:
    """Parse CSV text directly (upload path)."""
    import io
    import pandas as pd
    return Frame.from_pandas(pd.read_csv(io.StringIO(text)),
                             key=destination_frame)


def upload_numpy(arrays: Dict[str, np.ndarray],
                 categorical: Sequence[str] = (),
                 destination_frame: Optional[str] = None) -> Frame:
    return Frame.from_numpy(arrays, categorical=categorical,
                            key=destination_frame)
