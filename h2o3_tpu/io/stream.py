"""Streaming CSV → device ingest — the FileVec / chunked-parse path.

Reference: lazy byte Vecs over external files (water/fvec/FileVec.java:1)
feeding MultiFileParseTask chunk-at-a-time (water/parser/
ParseDataset.java:253), with cloud-wide categorical interning
(ParseDataset.java:356-440).

TPU shape of the same idea: the host reads fixed-size byte windows cut at
line boundaries, the native threaded tokenizer
(h2o3_tpu/native/csv_parser.cpp) parses each window, categorical levels
are interned incrementally against a global running domain, and each
column ships to HBM as ONE async `jax.device_put` of its assembled
padded array. Peak host memory is the file's BINARY columns (4 bytes a
cell), not the raw text; the raw CSV bytes never exist in RAM at once.
"""

from __future__ import annotations

import gzip
import os
from typing import Dict, IO, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.column import Column, T_CAT, T_NUM
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.parallel import mesh as mesh_mod
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.stream")

# 64MB windows: small enough that narrowed per-window blocks transfer
# WHILE the host tokenizes the next window (the wire through the axon
# tunnel sustains only ~15-20 MB/s, so hiding tokenize time behind it
# is the difference between adding and maxing the two costs)
DEFAULT_CHUNK_BYTES = 64 << 20


from functools import partial as _partial


def _open(path: str) -> IO[bytes]:
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def _iter_line_chunks(paths: List[str], chunk_bytes: int):
    """Yield (window, first_of_file) byte windows cut on newline
    boundaries; each file's first window starts at its header line."""
    for path in paths:
        rem = b""
        first_of_file = True
        with _open(path) as f:
            while True:
                buf = f.read(chunk_bytes)
                if not buf:
                    break
                buf = rem + buf
                cut = buf.rfind(b"\n")
                if cut < 0:
                    rem = buf
                    continue
                rem = buf[cut + 1:]
                yield buf[: cut + 1], first_of_file
                first_of_file = False
        if rem:
            yield (rem if rem.endswith(b"\n") else rem + b"\n"), \
                first_of_file


def _block_int_dtype(lo: float, hi: float):
    if -128 <= lo and hi <= 127:
        return np.int8
    if -32768 <= lo and hi <= 32767:
        return np.int16
    return np.int32


@_partial(jax.jit, static_argnames=("npad", "dtype", "sizes"))
def _assemble_col(parts, bit_parts, *, npad: int, dtype: str,
                  sizes: tuple):
    """Concatenate the per-window device blocks, upcast to the column's
    final dtype, pad, and build the NA mask from per-block packed bits
    (None = block had no NAs) — all on device. One program per
    (file-window-shape, dtype) signature; the persistent XLA cache
    amortizes it across runs."""
    segs = [p.astype(dtype) for p in parts]
    x = segs[0] if len(segs) == 1 else jnp.concatenate(segs)
    x = jnp.pad(x, (0, npad - x.shape[0]))
    x = jax.lax.with_sharding_constraint(x, mesh_mod.row_sharding())
    msegs = []
    for bits, sz in zip(bit_parts, sizes):
        if bits is None:
            msegs.append(jnp.zeros(sz, bool))
        else:
            idx = jnp.arange(sz, dtype=jnp.int32)
            b = bits[idx >> 3]
            msegs.append((
                (b >> (7 - (idx & 7)).astype(jnp.uint8)) & 1).astype(bool))
    m = msegs[0] if len(msegs) == 1 else jnp.concatenate(msegs)
    m = jnp.pad(m, (0, npad - m.shape[0]), constant_values=True)
    m = jax.lax.with_sharding_constraint(m, mesh_mod.row_sharding())
    return x, m


class _ColAcc:
    """Per-column accumulator: per-window NARROWED device blocks + the
    global categorical domain.

    Each window's slice ships immediately as an async device_put at the
    window-local narrow dtype (int8/int16 when the block's values fit —
    the NewChunk.compress codec role, applied per chunk like the
    reference), and NA masks ship as packed BITS only for blocks that
    have NAs. The wire through the tunneled chip is the ingest
    bottleneck (~15-20 MB/s measured), so bytes-on-wire is the budget:
    narrowing + bit-masks + transfer/tokenize overlap together turn
    sum(tokenize, transfer-at-4B/cell) into ~max(tokenize,
    transfer-at-1-2B/cell)."""

    def __init__(self, name: str):
        self.name = name
        self.parts: List[jax.Array] = []     # device blocks (async put)
        self.bit_parts: List[Optional[jax.Array]] = []
        self.sizes: List[int] = []
        self.levels: Dict[str, int] = {}     # global categorical domain
        self.order: List[str] = []
        self.is_cat = False

    def _push(self, clean: np.ndarray, na: np.ndarray, dtype):
        self.parts.append(jax.device_put(clean.astype(dtype, copy=False)))
        self.bit_parts.append(
            jax.device_put(np.packbits(na)) if na.any() else None)
        self.sizes.append(len(clean))

    def add_numeric(self, arr: np.ndarray):
        if self.is_cat:
            # numeric window inside a categorical column: values become
            # their string levels (the reference re-types the column)
            self.add_categorical(
                np.where(np.isnan(arr), -1, 0).astype(np.int32),
                [], raw_numeric=arr)
            return
        na = ~np.isfinite(arr)
        clean = np.where(na, 0.0, arr)
        # per-chunk integrality/range tracking for the FINAL dtype
        if not hasattr(self, "_all_int"):
            self._all_int, self._lo, self._hi = True, np.inf, -np.inf
        blk_int = np.all(clean == np.round(clean)) and \
            np.all(np.abs(clean) < 2**31)
        if self._all_int and blk_int:
            if clean.size:
                self._lo = min(self._lo, float(clean.min()))
                self._hi = max(self._hi, float(clean.max()))
        else:
            self._all_int = False
        if blk_int and clean.size:
            bd = _block_int_dtype(float(clean.min()), float(clean.max()))
        elif blk_int:
            bd = np.int8
        else:
            bd = np.float32
        self._push(clean, na, bd)

    def add_categorical(self, codes: np.ndarray, domain: List[str],
                        raw_numeric: Optional[np.ndarray] = None):
        if not self.is_cat and self.parts:
            # column promoted to categorical mid-stream: earlier numeric
            # blocks are fetched back and re-expressed as levels (rare
            # type-drift path; one host round trip per prior window —
            # the reference re-parses the column in the same situation)
            old = list(zip(self.parts, self.bit_parts, self.sizes))
            self.parts, self.bit_parts, self.sizes = [], [], []
            self.is_cat = True
            for part, bits, sz in old:
                vals = np.asarray(part, np.float64)
                if bits is not None:
                    na_old = np.unpackbits(
                        np.asarray(bits), count=sz).astype(bool)
                    vals[na_old] = np.nan
                self.add_categorical(np.zeros(0, np.int32), [],
                                     raw_numeric=vals)
        self.is_cat = True
        if raw_numeric is not None:
            strs = np.array([None if np.isnan(v) else
                             (f"{v:g}") for v in raw_numeric], object)
            codes = np.empty(len(strs), np.int32)
            for i, s in enumerate(strs):
                if s is None:
                    codes[i] = -1
                else:
                    k = self.levels.get(s)
                    if k is None:
                        k = self.levels[s] = len(self.order)
                        self.order.append(s)
                    codes[i] = k
            remapped = codes
        else:
            lut = np.empty(max(len(domain), 1), np.int32)
            for j, lvl in enumerate(domain):
                k = self.levels.get(lvl)
                if k is None:
                    k = self.levels[lvl] = len(self.order)
                    self.order.append(lvl)
                lut[j] = k
            remapped = np.where(codes >= 0, lut[np.maximum(codes, 0)], -1)
        na = remapped < 0
        clean = np.where(na, 0, remapped)
        # interning is append-only, so block codes are final; narrow by
        # the block's max level index (upcast to int32 at assembly)
        self._push(clean, na,
                   _block_int_dtype(0, float(clean.max(initial=0))))

    def finish(self, n: int, npad: int) -> Column:
        dtype = np.float32
        if self.is_cat:
            dtype = np.int32
        elif getattr(self, "_all_int", False):
            dtype = _block_int_dtype(self._lo, self._hi)
        data, na = _assemble_col(tuple(self.parts), tuple(self.bit_parts),
                                 npad=npad, dtype=np.dtype(dtype).name,
                                 sizes=tuple(self.sizes))
        self.parts, self.bit_parts, self.sizes = [], [], []
        if self.is_cat:
            return Column(name=self.name, type=T_CAT, data=data,
                          na_mask=na, nrows=n, domain=list(self.order))
        return Column(name=self.name, type=T_NUM, data=data,
                      na_mask=na, nrows=n)


def stream_import_csv(path, destination_frame: Optional[str] = None,
                      chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                      col_types: Optional[Dict[str, str]] = None) -> Frame:
    """Chunked native parse with overlapped async H2D transfer."""
    from h2o3_tpu.native import parse_csv_bytes
    paths = [path] if isinstance(path, str) else list(path)
    from h2o3_tpu import telemetry
    telemetry.counter("parse_files_total").inc(len(paths))
    try:
        telemetry.counter("parse_bytes_total").inc(
            sum(os.path.getsize(f) for f in paths))
    except OSError:
        pass
    accs: Dict[str, _ColAcc] = {}
    names: List[str] = []
    header_line = None
    total = 0
    first = True
    for window, first_of_file in _iter_line_chunks(paths, chunk_bytes):
        if first_of_file and not first and header_line and \
                window.startswith(header_line):
            # repeated header in files 2..N — drop it (the reference
            # parser likewise skips per-file headers)
            window = window[len(header_line):]
            if not window:
                continue
        res = parse_csv_bytes(window, header=first, decode=False)
        if res is None:
            raise RuntimeError("native csv parser unavailable")
        cols, domains = res
        if first:
            names = list(cols.keys())
            accs = {nm: _ColAcc(nm) for nm in names}
            nl = window.find(b"\n")
            header_line = window[: nl + 1] if nl >= 0 else None
            first = False
        else:
            # headerless windows come back as C1..Cn positionally
            cols = {names[j]: arr
                    for j, arr in enumerate(cols.values())}
            domains = {names[int(k[1:]) - 1] if k.startswith("C") else k: v
                       for k, v in domains.items()}
        nrows_w = len(next(iter(cols.values()))) if cols else 0
        total += nrows_w
        for nm in names:
            arr = cols[nm]
            forced = (col_types or {}).get(nm)
            if nm in domains or forced == "categorical":
                if nm in domains:
                    accs[nm].add_categorical(arr.astype(np.int32),
                                             domains[nm])
                else:
                    accs[nm].add_categorical(
                        np.zeros(0, np.int32), [],
                        raw_numeric=arr.astype(np.float64))
            else:
                accs[nm].add_numeric(np.asarray(arr, np.float64))
    npad = mesh_mod.padded_rows(total)
    columns = [accs[nm].finish(total, npad) for nm in names]
    fr = Frame(columns, total, key=destination_frame)
    log.info("stream-parsed %s -> %s (%d x %d)", paths[0], fr.key,
             fr.nrows, fr.ncols)
    return fr
