"""Streaming CSV → device ingest — the FileVec / chunked-parse path.

Reference: lazy byte Vecs over external files (water/fvec/FileVec.java:1)
feeding MultiFileParseTask chunk-at-a-time (water/parser/
ParseDataset.java:253), with cloud-wide categorical interning
(ParseDataset.java:356-440).

TPU shape of the same idea: the host reads fixed-size byte windows cut at
line boundaries, the native threaded tokenizer
(h2o3_tpu/native/csv_parser.cpp) parses each window, categorical levels
are interned incrementally against a global running domain, and each
column ships to HBM as ONE async `jax.device_put` of its assembled
padded array. Peak host memory is the file's BINARY columns (4 bytes a
cell), not the raw text; the raw CSV bytes never exist in RAM at once.
"""

from __future__ import annotations

import gzip
import os
from typing import Dict, IO, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.column import Column, T_CAT, T_NUM
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.parallel import mesh as mesh_mod
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.stream")

DEFAULT_CHUNK_BYTES = 256 << 20          # one parse window


def _open(path: str) -> IO[bytes]:
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def _iter_line_chunks(paths: List[str], chunk_bytes: int):
    """Yield (window, first_of_file) byte windows cut on newline
    boundaries; each file's first window starts at its header line."""
    for path in paths:
        rem = b""
        first_of_file = True
        with _open(path) as f:
            while True:
                buf = f.read(chunk_bytes)
                if not buf:
                    break
                buf = rem + buf
                cut = buf.rfind(b"\n")
                if cut < 0:
                    rem = buf
                    continue
                rem = buf[cut + 1:]
                yield buf[: cut + 1], first_of_file
                first_of_file = False
        if rem:
            yield (rem if rem.endswith(b"\n") else rem + b"\n"), \
                first_of_file


class _ColAcc:
    """Per-column accumulator: device chunk list + global domain."""

    def __init__(self, name: str):
        self.name = name
        self.parts: List[jax.Array] = []     # device arrays (async put)
        self.na_parts: List[jax.Array] = []
        self.levels: Dict[str, int] = {}     # global categorical domain
        self.order: List[str] = []
        self.is_cat = False

    def add_numeric(self, arr: np.ndarray):
        if self.is_cat:
            # numeric window inside a categorical column: values become
            # their string levels (the reference re-types the column)
            self.add_categorical(
                np.where(np.isnan(arr), -1, 0).astype(np.int32),
                [], raw_numeric=arr)
            return
        na = ~np.isfinite(arr)
        clean = np.where(na, 0.0, arr)
        # per-chunk integrality/range tracking for dtype narrowing at
        # finish (the NewChunk.compress codec-selection role)
        if not hasattr(self, "_all_int"):
            self._all_int, self._lo, self._hi = True, np.inf, -np.inf
        if self._all_int and np.all(clean == np.round(clean)) and \
                np.all(np.abs(clean) < 2**31):
            if clean.size:
                self._lo = min(self._lo, float(clean.min()))
                self._hi = max(self._hi, float(clean.max()))
        else:
            self._all_int = False
        self.parts.append(clean.astype(np.float32))
        self.na_parts.append(na)

    def add_categorical(self, codes: np.ndarray, domain: List[str],
                        raw_numeric: Optional[np.ndarray] = None):
        if not self.is_cat and self.parts:
            # column promoted to categorical mid-stream: earlier numeric
            # windows are fetched back and re-expressed as levels (rare
            # type-drift path; one host round trip per prior window —
            # the reference re-parses the column in the same situation)
            old_parts, old_nas = self.parts, self.na_parts
            self.parts, self.na_parts = [], []
            self.is_cat = True
            for part, na in zip(old_parts, old_nas):
                vals = np.asarray(part, np.float64)
                vals[np.asarray(na)] = np.nan
                self.add_categorical(np.zeros(0, np.int32), [],
                                     raw_numeric=vals)
        self.is_cat = True
        if raw_numeric is not None:
            strs = np.array([None if np.isnan(v) else
                             (f"{v:g}") for v in raw_numeric], object)
            codes = np.empty(len(strs), np.int32)
            for i, s in enumerate(strs):
                if s is None:
                    codes[i] = -1
                else:
                    k = self.levels.get(s)
                    if k is None:
                        k = self.levels[s] = len(self.order)
                        self.order.append(s)
                    codes[i] = k
            remapped = codes
        else:
            lut = np.empty(max(len(domain), 1), np.int32)
            for j, lvl in enumerate(domain):
                k = self.levels.get(lvl)
                if k is None:
                    k = self.levels[lvl] = len(self.order)
                    self.order.append(lvl)
                lut[j] = k
            remapped = np.where(codes >= 0, lut[np.maximum(codes, 0)], -1)
        na = remapped < 0
        self.parts.append(np.where(na, 0, remapped).astype(np.int32))
        self.na_parts.append(na)

    def finish(self, n: int, npad: int, shard) -> Column:
        """Assemble the padded column on HOST and ship it in ONE
        device_put. Device-side concatenate/pad/astype compiled a fresh
        XLA program per (window-shape, dtype) combination — ~6s of
        compiles on the first ingest of every new file size, which is
        what made measured ingest 5 MB/s while the steady state runs at
        ~80 MB/s. device_put has no compile and stays async."""
        dtype = np.float32
        if self.is_cat:
            dtype = np.int32
        elif getattr(self, "_all_int", False):
            # dtype-codec role of NewChunk.compress
            lo, hi = self._lo, self._hi
            if -128 <= lo and hi <= 127:
                dtype = np.int8
            elif -32768 <= lo and hi <= 32767:
                dtype = np.int16
            else:
                dtype = np.int32
        data_h = np.zeros(npad, dtype)
        na_h = np.ones(npad, bool)       # padding rows are NA-masked
        pos = 0
        for part, napart in zip(self.parts, self.na_parts):
            k = len(part)
            data_h[pos: pos + k] = part.astype(dtype, copy=False)
            na_h[pos: pos + k] = napart
            pos += k
        self.parts, self.na_parts = [], []
        data = jax.device_put(data_h, shard)
        na = jax.device_put(na_h, shard)
        if self.is_cat:
            return Column(name=self.name, type=T_CAT, data=data,
                          na_mask=na, nrows=n, domain=list(self.order))
        return Column(name=self.name, type=T_NUM, data=data,
                      na_mask=na, nrows=n)


def stream_import_csv(path, destination_frame: Optional[str] = None,
                      chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                      col_types: Optional[Dict[str, str]] = None) -> Frame:
    """Chunked native parse with overlapped async H2D transfer."""
    from h2o3_tpu.native import parse_csv_bytes
    paths = [path] if isinstance(path, str) else list(path)
    accs: Dict[str, _ColAcc] = {}
    names: List[str] = []
    header_line = None
    total = 0
    first = True
    for window, first_of_file in _iter_line_chunks(paths, chunk_bytes):
        if first_of_file and not first and header_line and \
                window.startswith(header_line):
            # repeated header in files 2..N — drop it (the reference
            # parser likewise skips per-file headers)
            window = window[len(header_line):]
            if not window:
                continue
        res = parse_csv_bytes(window, header=first, decode=False)
        if res is None:
            raise RuntimeError("native csv parser unavailable")
        cols, domains = res
        if first:
            names = list(cols.keys())
            accs = {nm: _ColAcc(nm) for nm in names}
            nl = window.find(b"\n")
            header_line = window[: nl + 1] if nl >= 0 else None
            first = False
        else:
            # headerless windows come back as C1..Cn positionally
            cols = {names[j]: arr
                    for j, arr in enumerate(cols.values())}
            domains = {names[int(k[1:]) - 1] if k.startswith("C") else k: v
                       for k, v in domains.items()}
        nrows_w = len(next(iter(cols.values()))) if cols else 0
        total += nrows_w
        for nm in names:
            arr = cols[nm]
            forced = (col_types or {}).get(nm)
            if nm in domains or forced == "categorical":
                if nm in domains:
                    accs[nm].add_categorical(arr.astype(np.int32),
                                             domains[nm])
                else:
                    accs[nm].add_categorical(
                        np.zeros(0, np.int32), [],
                        raw_numeric=arr.astype(np.float64))
            else:
                accs[nm].add_numeric(np.asarray(arr, np.float64))
    npad = mesh_mod.padded_rows(total)
    shard = mesh_mod.row_sharding()
    columns = [accs[nm].finish(total, npad, shard) for nm in names]
    fr = Frame(columns, total, key=destination_frame)
    log.info("stream-parsed %s -> %s (%d x %d)", paths[0], fr.key,
             fr.nrows, fr.ncols)
    return fr
