"""Streaming CSV → device ingest — the chunk-parallel MultiFileParseTask
path.

Reference: lazy byte Vecs over external files (water/fvec/FileVec.java:1)
feeding MultiFileParseTask chunk-at-a-time (water/parser/
ParseDataset.java:253), with cloud-wide categorical interning
(ParseDataset.java:356-440).

TPU shape of the same idea, now as a three-stage pipeline:

1. SPLIT (producer thread): the quote-aware splitter (io/chunking.py)
   reads fixed-size byte windows cut at record boundaries and strips
   repeated per-file headers, fanning windows to the tokenizer pool. A
   bounded queue gives backpressure, so at most workers+2 raw windows
   exist on the host at once (the memory-governor "no unbounded host
   buffering" contract), and each window passes chunk admission against
   the HBM budget before it is staged.
2. TOKENIZE (H2O3TPU_PARSE_WORKERS threads): each worker runs the
   native tokenizer (h2o3_tpu/native/csv_parser.cpp, single-threaded per
   window — the worker pool IS the parallelism knob) plus per-column
   dtype narrowing into NumericBlocks / categorical code blocks. ctypes
   and numpy release the GIL, so threads scale across host cores.
3. MERGE + TRANSFER (caller thread): windows merge strictly in order
   into per-column BlockAccumulators (frame/column.py) — global
   categorical interning, int/float narrowing reconciliation, and one
   async `jax.device_put` per block. A double-buffered transfer window
   waits on chunk N-2's device blocks before staging chunk N, so
   tokenize and H2D transfer overlap instead of running in lockstep.

Because the merge stage is the SAME code consuming the SAME windows in
the SAME order, the parallel path is bit-identical to the sequential
one (workers=1), which remains the exact fallback.
"""

from __future__ import annotations

import collections
import os
import queue
import threading
import time as _time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

import jax
import numpy as np

from h2o3_tpu.frame.column import (BlockAccumulator, block_values_f64,
                                   narrow_numeric_block)
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.io import chunking
from h2o3_tpu.io.chunking import DEFAULT_CHUNK_BYTES, iter_line_chunks
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.stream")

# chunks whose device blocks may still be in flight before the merge
# stage waits on the oldest — the double-buffer depth
_TRANSFER_DEPTH = 2

_DONE = object()


def _tokenize_window(window: bytes, is_first: bool):
    """Pure per-chunk stage: native tokenize + per-column narrowing.

    Runs on worker threads; touches no shared state. Returns
    (names_or_None, entries, nrows, seconds) where entries are
    positional per-column tuples — ('cat', int32 codes, window-local
    domain) or ('num', NumericBlock) — that the in-order merge maps to
    global column names.
    """
    from h2o3_tpu.native import parse_csv_bytes
    t0 = _time.perf_counter()
    res = parse_csv_bytes(window, header=is_first, decode=False,
                          nthreads=1)
    if res is None:
        raise RuntimeError("native csv parser unavailable")
    cols, domains = res
    names = list(cols.keys()) if is_first else None
    entries = []
    for nm, arr in cols.items():
        if nm in domains:
            entries.append(("cat", arr.astype(np.int32, copy=False),
                            domains[nm]))
        else:
            entries.append(("num",
                            narrow_numeric_block(np.asarray(arr,
                                                            np.float64))))
    nrows = len(next(iter(cols.values()))) if cols else 0
    return names, entries, nrows, _time.perf_counter() - t0


class _MergeState:
    """The in-order merge stage: owns the per-column accumulators.

    One instance per parse; fed window results strictly in window order
    by both the sequential and parallel drivers, so the resulting
    frames/domains/dtypes are identical regardless of worker count.
    """

    def __init__(self, col_types: Optional[Dict[str, str]]):
        self.col_types = col_types or {}
        self.accs: Dict[str, BlockAccumulator] = {}
        self.names: List[str] = []
        self.total = 0

    def merge(self, names: Optional[List[str]], entries, nrows: int):
        if names is not None and not self.names:
            self.names = names
            self.accs = {nm: BlockAccumulator(nm) for nm in names}
        self.total += nrows
        for nm, entry in zip(self.names, entries):
            acc = self.accs[nm]
            if entry[0] == "cat":
                acc.add_categorical(entry[1], entry[2])
            elif self.col_types.get(nm) == "categorical":
                acc.add_categorical(np.zeros(0, np.int32), [],
                                    raw_numeric=block_values_f64(entry[1]))
            else:
                acc.add_numeric_block(entry[1])

    def new_device_parts(self, prev_counts: Dict[str, int]) -> list:
        """Device arrays pushed since `prev_counts` — one transfer
        ticket for the double-buffer."""
        out = []
        for nm, acc in self.accs.items():
            start = min(prev_counts.get(nm, 0), len(acc.parts))
            out.extend(acc.parts[start:])
            out.extend(b for b in acc.bit_parts[start:] if b is not None)
        return out

    def part_counts(self) -> Dict[str, int]:
        return {nm: len(acc.parts) for nm, acc in self.accs.items()}


def _admit_chunk(nbytes: int) -> None:
    """PR 11 memory-governor chunk admission: before staging another
    window's blocks toward HBM, make room by spilling cold frames (never
    rejects mid-parse — eviction is the pressure valve here)."""
    try:
        from h2o3_tpu.core.memgov import governor
        if governor.governed():
            governor.evict_for_admission(nbytes)
    except Exception:           # admission is best-effort, parse wins
        pass


class _TransferWindow:
    """Double-buffered transfer stage: bounds in-flight device blocks to
    ~_TRANSFER_DEPTH chunks so async device_put overlaps tokenize
    without unbounded staging, and times the waits as stage=transfer."""

    def __init__(self, hist):
        self._tickets = collections.deque()
        self._hist = hist

    def add(self, parts: list) -> None:
        if parts:
            self._tickets.append(parts)
        while len(self._tickets) > _TRANSFER_DEPTH:
            self._wait_one()

    def drain(self) -> None:
        while self._tickets:
            self._wait_one()

    def _wait_one(self) -> None:
        parts = self._tickets.popleft()
        t0 = _time.perf_counter()
        jax.block_until_ready(parts)
        self._hist(stage="transfer").observe(_time.perf_counter() - t0)


def _consume(state: _MergeState, result, hist, transfer: "_TransferWindow",
             cancel_point) -> None:
    """Shared merge step for both drivers: cancellation check, in-order
    accumulator merge, transfer ticketing."""
    cancel_point("parse.chunk")
    names, entries, nrows, tok_s = result
    hist(stage="tokenize").observe(tok_s)
    before = state.part_counts()
    t0 = _time.perf_counter()
    state.merge(names, entries, nrows)
    hist(stage="merge").observe(_time.perf_counter() - t0)
    transfer.add(state.new_device_parts(before))


def _run_sequential(paths: List[str], chunk_bytes: int, state: _MergeState,
                    hist, transfer, cancel_point) -> None:
    for window, is_first in iter_line_chunks(paths, chunk_bytes):
        _admit_chunk(len(window))
        _consume(state, _tokenize_window(window, is_first), hist,
                 transfer, cancel_point)


def _run_parallel(paths: List[str], chunk_bytes: int, state: _MergeState,
                  nworkers: int, hist, transfer, cancel_point) -> None:
    """Producer → tokenizer pool → in-order merge. The bounded queue is
    the backpressure: at most nworkers+2 windows (raw bytes or parsed
    blocks) live on the host at once."""
    q: "queue.Queue" = queue.Queue(maxsize=nworkers + 2)
    stop = threading.Event()
    pool = ThreadPoolExecutor(max_workers=nworkers,
                              thread_name_prefix="parse-tok")

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _producer():
        try:
            for window, is_first in iter_line_chunks(paths, chunk_bytes):
                if stop.is_set():
                    return
                _admit_chunk(len(window))
                if not _put(pool.submit(_tokenize_window, window,
                                        is_first)):
                    return
        except BaseException as e:          # surface read errors in merge
            _put(e)
        finally:
            _put(_DONE)

    prod = threading.Thread(target=_producer, name="parse-split",
                            daemon=True)
    prod.start()
    try:
        while True:
            item = q.get()
            if item is _DONE:
                break
            if isinstance(item, BaseException):
                raise item
            _consume(state, item.result(), hist, transfer, cancel_point)
    finally:
        stop.set()
        while True:                          # unblock a stuck producer
            try:
                q.get_nowait()
            except queue.Empty:
                break
        prod.join(timeout=10.0)
        pool.shutdown(wait=True, cancel_futures=True)


def stream_import_csv(path, destination_frame: Optional[str] = None,
                      chunk_bytes: Optional[int] = None,
                      col_types: Optional[Dict[str, str]] = None,
                      workers: Optional[int] = None) -> Frame:
    """Chunk-parallel native parse with overlapped async H2D transfer.

    ``workers`` (default: H2O3TPU_PARSE_WORKERS / host cores) sizes the
    tokenizer pool; workers=1 runs the exact sequential fallback. Both
    paths produce bit-identical frames (data, dtypes, domains, NA
    masks).
    """
    from h2o3_tpu import telemetry
    from h2o3_tpu.core.request_ctx import cancel_point
    paths = chunking.expand_paths(path)
    if not paths or not all(os.path.exists(f) for f in paths):
        raise FileNotFoundError(str(path))
    nworkers = chunking.resolve_workers(workers)
    cbytes = chunking.resolve_chunk_bytes(chunk_bytes)
    telemetry.counter("parse_files_total").inc(len(paths))
    try:
        telemetry.counter("parse_bytes_total").inc(
            sum(os.path.getsize(f) for f in paths))
        for f in paths:
            telemetry.counter(
                "ingest_bytes_total",
                format=chunking.classify_format(f)).inc(
                    os.path.getsize(f))
    except OSError:
        pass

    def hist(**labels):
        return telemetry.histogram("parse_chunk_seconds", **labels)

    state = _MergeState(col_types)
    transfer = _TransferWindow(hist)
    mode = "sequential" if nworkers == 1 else "chunk-parallel"
    with telemetry.span("parse.stream", mode=mode, workers=nworkers,
                        files=len(paths)):
        if nworkers == 1:
            _run_sequential(paths, cbytes, state, hist, transfer,
                            cancel_point)
        else:
            _run_parallel(paths, cbytes, state, nworkers, hist, transfer,
                          cancel_point)
        transfer.drain()
    telemetry.counter("ingest_rows_total").inc(state.total)
    fr = Frame.from_blocks(state.accs, state.names, state.total,
                           key=destination_frame)
    log.info("stream-parsed %s -> %s (%d x %d, %s, workers=%d)",
             paths[0], fr.key, fr.nrows, fr.ncols, mode, nworkers)
    return fr
