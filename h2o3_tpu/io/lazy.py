"""Lazy file-backed frames — the water/fvec FileVec role.

Reference: water/fvec/FileVec.java:1 — a Vec whose bytes stay in the
backing file until a chunk is actually touched, so cold data costs no
memory. TPU twin: a ``FileBackedFrame`` DKV stub holding only the
source paths + header metadata; the first ``DKV.get`` parses the file
into a real (HBM-resident) Frame. The Cleaner closes the loop: frames
that came from a file and were never mutated EVICT back to this stub
under memory pressure — no spill npz write needed, the source file IS
the ice copy — capping the total working set at HBM size while the
catalog of imported frames stays unbounded.
"""

from __future__ import annotations

from typing import List, Optional

from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.lazy")


class FileBackedFrame:
    """DKV stub for a frame whose data still lives in its source file."""

    _is_lazy_stub = True

    def __init__(self, key: str, source: str,
                 paths: Optional[List[str]] = None,
                 names: Optional[List[str]] = None,
                 nrows: Optional[int] = None, nbytes: int = 0,
                 parse_kwargs: Optional[dict] = None):
        self.key = key
        self.source = source             # original path/glob, re-expanded
        self.paths = list(paths or [source])   # expanded (metadata only)
        self.names = names or []
        self.nrows = nrows
        self.nbytes = nbytes             # on-disk size (catalog display)
        self.parse_kwargs = parse_kwargs or {}

    def restore(self):
        # the eager parser handles globs / multi-file concat itself, so
        # the stub re-presents the ORIGINAL source string — per-file
        # restore would silently truncate multi-file imports
        from h2o3_tpu.io.parser import import_file
        fr = import_file(self.source, destination_frame=self.key,
                         **self.parse_kwargs)
        log.info("materialized lazy frame %s from %s (%d x %d)",
                 self.key, self.source, fr.nrows, fr.ncols)
        return fr

    def discard(self) -> None:
        """Nothing to reclaim — the backing file is user data."""


def sniff_meta(path: str, header=None):
    """(names, nrows, nbytes) as cheaply as the format allows: parquet
    from footer metadata, CSV from the header line + a buffered newline
    count; None where the format would require a full parse.
    ``header`` carries the caller's explicit choice so the stub metadata
    agrees with the frame the materializing parse will build."""
    import os
    nbytes = os.path.getsize(path)
    if path.endswith((".parquet", ".pq")):
        import pyarrow.parquet as pq
        pf = pq.ParquetFile(path)
        return list(pf.schema_arrow.names), pf.metadata.num_rows, nbytes
    if path.endswith(".csv"):
        import csv as _csv
        from h2o3_tpu.io.parser import guess_header
        with open(path, "rb") as f:
            first_line = f.readline().decode("utf-8", "replace")
            n = 0
            last = b"\n"
            while True:
                blk = f.read(1 << 20)
                if not blk:
                    break
                n += blk.count(b"\n")
                last = blk[-1:]
            if last != b"\n":
                n += 1                       # unterminated final row
        # csv.reader handles quoted commas in the header; nrows is an
        # UPPER BOUND when quoted fields embed newlines (exact count
        # would need a full tokenize — the stub metadata is advisory,
        # the materializing parse is authoritative)
        names = next(_csv.reader([first_line]), [])
        names = [c.strip() for c in names]
        has_header = guess_header(path) if header is None else bool(header)
        if not has_header:
            names = [f"C{i + 1}" for i in range(len(names))]
        return names, n + (0 if has_header else 1), nbytes
    return None, None, nbytes
