"""Byte-range chunk planning for the parallel ingest pipeline.

Reference: water/fvec/FileVec.java chunking + the ParseSetup plan that
MultiFileParseTask executes — the byte-range splitter that fans file
chunks out to tokenizer workers on their home nodes
(water/parser/ParseDataset.java:253).

Deliberately jax-free: the bench stub planner and the REST
/3/ParseSetup plan report both run without a backend, so this module
must import without initialising one.

Splitting contract: windows are cut at the last newline sitting at even
double-quote parity (RFC4180 — an escaped "" toggles parity twice), so a
quoted field containing the separator or an embedded newline never
straddles a window, and every window starts at a record boundary. gzip
members cannot be range-split, so .gz files fall back to streamed
re-chunking through the same cutter.
"""

from __future__ import annotations

import glob as _glob
import gzip
import os
from typing import IO, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

# 64MB windows: small enough that narrowed per-window blocks transfer
# WHILE workers tokenize the next windows (parse/transfer overlap), big
# enough that per-window tokenizer startup cost is noise.
DEFAULT_CHUNK_BYTES = 64 << 20


def resolve_workers(explicit: Optional[int] = None) -> int:
    """Tokenizer pool size: explicit arg > H2O3TPU_PARSE_WORKERS env >
    ARGS.parse_workers. 0 means one worker per host core; floor 1.
    workers=1 selects the exact sequential fallback path."""
    if explicit is not None:
        v = int(explicit)
    else:
        env = os.environ.get("H2O3TPU_PARSE_WORKERS")
        if env is not None:
            v = int(env)
        else:
            from h2o3_tpu.core.config import ARGS
            v = int(getattr(ARGS, "parse_workers", 0))
    return v if v > 0 else (os.cpu_count() or 1)


def resolve_chunk_bytes(explicit: Optional[int] = None) -> int:
    """Window size in bytes: explicit arg > H2O3TPU_PARSE_CHUNK_MB env >
    ARGS.parse_chunk_mb (MB)."""
    if explicit is not None:
        return max(int(explicit), 1)
    env = os.environ.get("H2O3TPU_PARSE_CHUNK_MB")
    if env is not None:
        return max(int(env), 1) << 20
    from h2o3_tpu.core.config import ARGS
    return max(int(getattr(ARGS, "parse_chunk_mb", 64)), 1) << 20


def quote_aware_cut(buf: bytes) -> int:
    """Index one past the LAST newline at even double-quote parity, or 0
    when the window holds no record boundary.

    A newline preceded by an even number of '"' bytes is outside any
    quoted field (windows always start at a record boundary, so parity 0
    at offset 0 is exact; RFC4180 "" escapes toggle twice and cancel).
    """
    a = np.frombuffer(buf, np.uint8)
    nl = np.flatnonzero(a == 0x0A)          # b"\n"
    if nl.size == 0:
        return 0
    q = np.flatnonzero(a == 0x22)           # b'"'
    if q.size == 0:
        return int(nl[-1]) + 1
    ok = nl[(np.searchsorted(q, nl) & 1) == 0]
    return int(ok[-1]) + 1 if ok.size else 0


def _open(path: str) -> IO[bytes]:
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def iter_line_chunks(paths: Sequence[str],
                     chunk_bytes: int) -> Iterator[Tuple[bytes, bool]]:
    """Yield (window, is_first_window) quote-aware newline-aligned byte
    windows across `paths`.

    Only the very first window carries a header line; repeated header
    lines at the start of files 2..N are stripped HERE so the sequential
    and parallel consumers see byte-identical windows (the reference
    parser likewise skips per-file headers, ParseDataset.java).
    """
    header_line: Optional[bytes] = None
    first = True

    def _emit(window: bytes, first_of_file: bool):
        nonlocal header_line, first
        if first_of_file and not first and header_line and \
                window.startswith(header_line):
            window = window[len(header_line):]
        if not window:
            return None
        if first:
            nl = window.find(b"\n")
            header_line = window[: nl + 1] if nl >= 0 else None
        out = (window, first)
        first = False
        return out

    for path in paths:
        rem = b""
        first_of_file = True
        with _open(path) as f:
            while True:
                buf = f.read(chunk_bytes)
                if not buf:
                    break
                buf = rem + buf
                cut = quote_aware_cut(buf)
                if cut <= 0:
                    rem = buf
                    continue
                rem = buf[cut:]
                out = _emit(buf[:cut], first_of_file)
                first_of_file = False
                if out is not None:
                    yield out
        if rem:
            out = _emit(rem if rem.endswith(b"\n") else rem + b"\n",
                        first_of_file)
            if out is not None:
                yield out


_ARROW_FORMATS = ("parquet", "orc", "avro")


def classify_format(path: str) -> str:
    """Coarse source-format label (telemetry + plan reporting)."""
    p = path.lower()
    if p.endswith(".gz"):
        return "csv.gz"
    ext = os.path.splitext(p)[1]
    return {
        ".parquet": "parquet", ".pq": "parquet",
        ".orc": "orc", ".avro": "avro",
        ".svmlight": "svmlight", ".svm": "svmlight",
        ".arff": "arff", ".xlsx": "xlsx",
    }.get(ext, "csv")


def expand_paths(paths: Union[str, Sequence[str]]) -> List[str]:
    """Glob-expand source patterns (sorted, like the import layer)."""
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)) or [p])
        elif os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")))
        else:
            out.append(p)
    return out


def parse_plan(paths: Union[str, Sequence[str]],
               chunk_bytes: Optional[int] = None,
               workers: Optional[int] = None) -> dict:
    """Describe how the ingest pipeline would run over `paths` — the
    plan surfaced by /3/ParseSetup, /3/Parse and the bench stub."""
    expanded = expand_paths(paths)
    fmts = sorted({classify_format(p) for p in expanded}) or ["csv"]
    w = resolve_workers(workers)
    cb = resolve_chunk_bytes(chunk_bytes)
    if fmts and all(f in _ARROW_FORMATS for f in fmts):
        mode = "arrow-columnar"
    elif w == 1:
        mode = "sequential"
    else:
        mode = "chunk-parallel"
    try:
        total: Optional[int] = sum(os.path.getsize(p) for p in expanded)
    except OSError:
        total = None
    est = (max(1, (total + cb - 1) // cb) if total else None)
    return {"mode": mode, "workers": w, "chunk_bytes": cb,
            "formats": fmts, "files": len(expanded),
            "source_bytes": total, "est_chunks": est}
