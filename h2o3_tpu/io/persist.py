"""Persist — pluggable byte-storage drivers + binary Frame/Model export.

Reference: water/persist/PersistManager.java:1 with Persist{FS,NFS,Hex,
EagerHTTP} drivers and the separate h2o-persist-{s3,hdfs,gcs} modules;
binary Frame export is water/fvec/persist/FramePersist.java; model
binary export/import is water/api's SaveModel/LoadModel on top of Iced
serialization.

TPU-native shape: drivers resolve a URI scheme to read/write byte blobs
(file:// and bare paths; hex:// = the node's ice/spill dir; http(s)://
read-only; s3://+gs:// raise with instructions unless a driver module
registers itself — this environment has no egress). Frames serialize as
one npz of dtype-narrowed columns + a JSON header (the chunk layout is
reconstructed by the mesh on load, so a frame saved on an 8-device mesh
loads fine on 1 device and vice versa). Models serialize via pickle with
every jax.Array lowered to numpy so checkpoints are device-independent
(the Iced/AutoBuffer role).
"""

from __future__ import annotations

import io
import json
import os
import pickle
import tempfile
from typing import Callable, Dict, List, Optional

import numpy as np

from h2o3_tpu.parallel.mesh import fetch_replicated as _fetch_np

from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.persist")


# ------------------------------------------------------------------ drivers

class PersistDriver:
    scheme = ""

    def read(self, uri: str) -> bytes:
        raise NotImplementedError

    def write(self, uri: str, data: bytes) -> None:
        raise NotImplementedError

    def exists(self, uri: str) -> bool:
        raise NotImplementedError

    def delete(self, uri: str) -> None:
        raise NotImplementedError

    def list(self, uri: str) -> List[str]:
        raise NotImplementedError


class _FileDriver(PersistDriver):
    scheme = "file"

    def _path(self, uri: str) -> str:
        return uri[7:] if uri.startswith("file://") else uri

    def read(self, uri: str) -> bytes:
        with open(self._path(uri), "rb") as f:
            return f.read()

    def write(self, uri: str, data: bytes) -> None:
        p = self._path(uri)
        os.makedirs(os.path.dirname(os.path.abspath(p)), exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, p)   # atomic publish (PersistFS atomicity contract)

    def exists(self, uri: str) -> bool:
        return os.path.exists(self._path(uri))

    def delete(self, uri: str) -> None:
        p = self._path(uri)
        if os.path.exists(p):
            os.remove(p)

    def list(self, uri: str) -> List[str]:
        p = self._path(uri)
        if not os.path.isdir(p):
            return []
        return sorted(os.path.join(p, f) for f in os.listdir(p))


class _IceDriver(_FileDriver):
    """hex:// — the node's ice (spill/checkpoint) directory
    (water/persist/PersistHex.java role)."""

    scheme = "hex"

    def __init__(self):
        self.root = os.environ.get(
            "H2O3_TPU_ICE_DIR",
            os.path.join(tempfile.gettempdir(), "h2o3_tpu_ice"))

    def _path(self, uri: str) -> str:
        rel = uri[6:] if uri.startswith("hex://") else uri
        return os.path.join(self.root, rel)


class _HTTPDriver(PersistDriver):
    """Read-only HTTP(S) ingest (water/persist/PersistEagerHTTP)."""

    scheme = "http"

    def read(self, uri: str) -> bytes:
        from urllib.request import urlopen
        with urlopen(uri, timeout=60) as r:
            return r.read()

    def exists(self, uri: str) -> bool:
        from urllib.request import Request, urlopen
        try:
            with urlopen(Request(uri, method="HEAD"), timeout=30) as r:
                return 200 <= r.status < 400
        except Exception:
            return False

    def write(self, uri: str, data: bytes) -> None:
        raise IOError("HTTP persist is read-only")

    def delete(self, uri: str) -> None:
        raise IOError("HTTP persist is read-only")

    def list(self, uri: str) -> List[str]:
        return [uri]


class _ArrowFsDriver(PersistDriver):
    """Cloud object stores over pyarrow's C++ filesystems — the
    h2o-persist-{s3,gcs,hdfs} modules' role. The filesystem is built
    lazily on first use: construction picks up ambient credentials
    (AWS_* env / instance metadata, GOOGLE_APPLICATION_CREDENTIALS,
    libhdfs config) exactly like the reference drivers read
    core-site.xml / AWS credential chains.
    """

    def __init__(self, scheme: str):
        self.scheme = scheme
        self._fs = None

    def _filesystem(self, uri: str = ""):
        if self.scheme == "hdfs":
            # per-authority connections: hdfs://namenode:8020/user/x must
            # connect to that namenode, not a global default
            from urllib.parse import urlsplit

            from pyarrow import fs as pafs
            auth = urlsplit(uri).netloc or "default"
            if self._fs is None:
                self._fs = {}
            if auth not in self._fs:
                self._fs[auth] = pafs.HadoopFileSystem.from_uri(
                    f"hdfs://{auth}")
            return self._fs[auth]
        if self._fs is None:
            from pyarrow import fs as pafs
            if self.scheme == "s3":
                self._fs = pafs.S3FileSystem()
            elif self.scheme in ("gs", "gcs"):
                self._fs = pafs.GcsFileSystem()
            else:
                raise IOError(f"unknown arrow fs scheme {self.scheme}")
        return self._fs

    def _path(self, uri: str) -> str:
        rest = uri.split("://", 1)[1]
        if self.scheme == "hdfs":
            # drop the authority: the path starts at the first '/'
            slash = rest.find("/")
            return rest[slash:] if slash >= 0 else "/"
        return rest     # s3/gs: bucket is the path prefix

    def read(self, uri: str) -> bytes:
        with self._filesystem(uri).open_input_stream(self._path(uri)) as f:
            return f.read()

    def write(self, uri: str, data: bytes) -> None:
        with self._filesystem(uri).open_output_stream(self._path(uri)) as f:
            f.write(data)

    def exists(self, uri: str) -> bool:
        from pyarrow import fs as pafs
        info = self._filesystem(uri).get_file_info(self._path(uri))
        return info.type != pafs.FileType.NotFound

    def delete(self, uri: str) -> None:
        self._filesystem(uri).delete_file(self._path(uri))

    def list(self, uri: str) -> List[str]:
        from pyarrow import fs as pafs
        sel = pafs.FileSelector(self._path(uri), recursive=False,
                                allow_not_found=True)
        return [f"{self.scheme}://{i.path}"
                for i in self._filesystem(uri).get_file_info(sel)]


class PersistManager:
    """Scheme → driver dispatch (water/persist/PersistManager.java:1)."""

    def __init__(self):
        self._drivers: Dict[str, PersistDriver] = {}
        fd = _FileDriver()
        self.register(fd)
        self.register(_IceDriver())
        http = _HTTPDriver()
        self._drivers["http"] = http
        self._drivers["https"] = http
        for scheme in ("s3", "gs", "gcs", "hdfs"):
            self._drivers[scheme] = _ArrowFsDriver(scheme)
        self._default = fd

    def register(self, driver: PersistDriver) -> None:
        self._drivers[driver.scheme] = driver

    def driver_for(self, uri: str) -> PersistDriver:
        if "://" in uri:
            scheme = uri.split("://", 1)[0].lower()
            d = self._drivers.get(scheme)
            if d is None:
                raise IOError(
                    f"no persist driver for scheme '{scheme}://' — register "
                    "one via persist_manager.register() (built in: "
                    "file/hex/http/s3/gs/hdfs)")
            return d
        return self._default

    def read(self, uri: str) -> bytes:
        return self.driver_for(uri).read(uri)

    def write(self, uri: str, data: bytes) -> None:
        self.driver_for(uri).write(uri, data)

    def exists(self, uri: str) -> bool:
        return self.driver_for(uri).exists(uri)

    def delete(self, uri: str) -> None:
        self.driver_for(uri).delete(uri)

    def list(self, uri: str) -> List[str]:
        return self.driver_for(uri).list(uri)


persist_manager = PersistManager()


# ------------------------------------------------------------------ frames

_FRAME_MAGIC = "h2o3tpu-frame-v1"


def frame_to_bytes(frame) -> bytes:
    """Device-independent frame blocks as one byte blob — the codec
    under :func:`save_frame`, the durability mirror, and the cloud
    checkpoint (all three share the bit-parity round-trip contract)."""
    header = {"magic": _FRAME_MAGIC, "nrows": frame.nrows,
              "names": list(frame.names), "types": {}, "domains": {}}
    arrays = {}
    for i, name in enumerate(frame.names):
        c = frame.col(name)
        header["types"][name] = c.type
        if c.domain is not None:
            header["domains"][name] = list(c.domain)
        if c.type == "string":
            s = c.strings[: c.nrows]
            mask = np.array([x is None for x in s], dtype=bool)
            arrays[f"c{i}"] = np.where(mask, "", s).astype("U")
            arrays[f"m{i}"] = mask
        else:
            arrays[f"c{i}"] = _fetch_np(c.data)[: c.nrows]
            arrays[f"m{i}"] = _fetch_np(c.na_mask)[: c.nrows]
    buf = io.BytesIO()
    np.savez_compressed(buf, __header__=np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8), **arrays)
    return buf.getvalue()


def save_frame(frame, uri: str) -> str:
    """Binary frame export (water/fvec/persist/FramePersist.saveTo)."""
    persist_manager.write(uri, frame_to_bytes(frame))
    return uri


def frame_from_bytes(data: bytes, key: Optional[str] = None):
    """Inverse of :func:`frame_to_bytes`; round-trips through
    Frame.from_numpy so the mesh rebuilds the chunk layout."""
    from h2o3_tpu.frame.frame import Frame
    npz = np.load(io.BytesIO(data), allow_pickle=False)
    header = json.loads(bytes(npz["__header__"]).decode())
    if header.get("magic") != _FRAME_MAGIC:
        raise IOError("blob is not an h2o3-tpu frame export")
    cols: Dict[str, np.ndarray] = {}
    domains: Dict[str, List[str]] = {}
    cats: List[str] = []
    strs: List[str] = []
    for i, name in enumerate(header["names"]):
        t = header["types"][name]
        if t == "string":
            s = npz[f"c{i}"].astype(object)
            # frames saved before masks existed have no m{i}: all-valid
            if f"m{i}" in npz.files:
                s[npz[f"m{i}"]] = None
            cols[name] = s
            strs.append(name)
        elif t == "categorical":
            codes = npz[f"c{i}"].astype(np.int32)
            if f"m{i}" in npz.files:
                codes = np.where(npz[f"m{i}"], -1, codes)
            cols[name] = codes
            domains[name] = header["domains"][name]
            cats.append(name)
        else:   # numeric (incl. time columns, stored as epoch numerics)
            v = npz[f"c{i}"].astype(np.float64)
            v = np.where(npz[f"m{i}"], np.nan, v)
            cols[name] = v
    return Frame.from_numpy(cols, categorical=cats, domains=domains,
                            strings=strs, key=key)


def load_frame(uri: str, key: Optional[str] = None):
    """Binary frame import (FramePersist.loadFrom)."""
    return frame_from_bytes(persist_manager.read(uri), key=key)


# ------------------------------------------------------------------ models

class _DeviceLoweringPickler(pickle.Pickler):
    """Pickle with every jax.Array lowered to host numpy — checkpoints are
    device-independent (the Iced/AutoBuffer serialization role)."""

    def reducer_override(self, obj):
        import jax
        if isinstance(obj, jax.Array):
            if not obj.is_fully_addressable:
                if obj.is_fully_replicated:
                    # Replicated: the local shard IS the global value
                    # (np.asarray on the global array would raise).
                    return (np.asarray,
                            (np.asarray(obj.addressable_shards[0].data),))
                # Cross-process sharded: allgather like
                # mesh.fetch_replicated. COLLECTIVE — dumping an object
                # holding such arrays is an SPMD point (every process
                # must dump the same object graph), which save paths on
                # a multi-process cloud already are.
                from h2o3_tpu.parallel.mesh import fetch_replicated
                return (np.asarray, (np.asarray(fetch_replicated(obj)),))
            return (np.asarray, (np.asarray(obj),))
        return NotImplemented


def model_to_bytes(model) -> bytes:
    """Device-lowered model binary — the codec under
    :func:`save_model` and the cloud checkpoint."""
    buf = io.BytesIO()
    _DeviceLoweringPickler(buf, protocol=pickle.HIGHEST_PROTOCOL).dump(model)
    return buf.getvalue()


def save_model(model, uri: str) -> str:
    """Full binary model save (REST SaveModel role) — unlike MOJO export
    this keeps params/metrics/output and is re-trainable via checkpoint."""
    persist_manager.write(uri, model_to_bytes(model))
    return uri


def model_from_bytes(data: bytes):
    """Inverse of :func:`model_to_bytes`; re-registers in DKV."""
    from h2o3_tpu.core.kv import DKV
    model = pickle.loads(data)
    DKV.put(model.key, model)
    return model


def load_model(uri: str):
    """Binary model load (REST LoadModel role); re-registers in DKV."""
    return model_from_bytes(persist_manager.read(uri))
