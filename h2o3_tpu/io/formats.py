"""Additional ingest formats — SVMLight and ARFF.

Reference: water/parser/SVMLightParser.java and ARFFParser.java (both
built-in parser types next to CSV; water/parser/ParseSetup.java
auto-detects them from content). Both decode on the host into dense
columns — the reference likewise densifies SVMLight into a Frame whose
trailing columns are zero-filled.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu.frame.frame import Frame


def parse_svmlight(text: str, key: Optional[str] = None) -> Frame:
    """``label idx:val idx:val …`` lines → dense Frame with a C0
    target column (1-based feature indices, reference SVMLightParser)."""
    labels: List[float] = []
    rows: List[Dict[int, float]] = []
    max_idx = 0
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split()
        labels.append(float(parts[0]))
        row: Dict[int, float] = {}
        for tok in parts[1:]:
            if tok.startswith("qid:"):
                continue
            idx, val = tok.split(":", 1)
            i = int(idx)
            if i < 1:
                raise ValueError(f"SVMLight indices are 1-based, got {i}")
            row[i] = float(val)
            max_idx = max(max_idx, i)
        rows.append(row)
    n = len(rows)
    dense = np.zeros((n, max_idx), dtype=np.float64)
    for r, row in enumerate(rows):
        for i, v in row.items():
            dense[r, i - 1] = v
    cols = {"C0": np.asarray(labels)}
    for j in range(max_idx):
        cols[f"C{j + 1}"] = dense[:, j]
    return Frame.from_numpy(cols, key=key)


_ARFF_ATTR = re.compile(r"@attribute\s+('[^']+'|\S+)\s+(.+)", re.IGNORECASE)


def _split_arff_row(line: str) -> List[str]:
    """Comma split honoring single-quoted values (reference ARFFParser
    quoting rules) — `5.1,'a, b',x` → three fields."""
    out, cur, q = [], [], False
    for ch in line:
        if ch == "'":
            q = not q
            cur.append(ch)
        elif ch == "," and not q:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


def parse_arff(text: str, key: Optional[str] = None) -> Frame:
    """ARFF (@relation/@attribute/@data) → Frame with nominal attributes
    interned as categoricals (reference ARFFParser)."""
    names: List[str] = []
    kinds: List[Tuple[str, Optional[List[str]]]] = []  # (numeric|nominal|string, levels)
    data_lines: List[str] = []
    in_data = False
    for line in text.splitlines():
        s = line.strip()
        if not s or s.startswith("%"):
            continue
        low = s.lower()
        if in_data:
            data_lines.append(s)
            continue
        if low.startswith("@relation"):
            continue
        if low.startswith("@attribute"):
            m = _ARFF_ATTR.match(s)
            if not m:
                raise ValueError(f"bad @attribute line: {s}")
            name = m.group(1).strip("'")
            spec = m.group(2).strip()
            if spec.startswith("{"):
                levels = [v.strip().strip("'") for v in
                          spec.strip("{}").split(",")]
                kinds.append(("nominal", levels))
            elif spec.lower() in ("numeric", "real", "integer"):
                kinds.append(("numeric", None))
            elif spec.lower() == "string":
                kinds.append(("string", None))
            else:   # date etc → treat as string
                kinds.append(("string", None))
            names.append(name)
            continue
        if low.startswith("@data"):
            in_data = True
    if not in_data:
        raise ValueError("no @data section")

    n = len(data_lines)
    cols: Dict[str, np.ndarray] = {}
    raw = [_split_arff_row(ln) for ln in data_lines]
    cats: List[str] = []
    strs: List[str] = []
    domains: Dict[str, List[str]] = {}
    for j, (name, (kind, levels)) in enumerate(zip(names, kinds)):
        vals = [r[j].strip().strip("'") if j < len(r) else "?" for r in raw]
        if kind == "numeric":
            cols[name] = np.asarray(
                [np.nan if v == "?" else float(v) for v in vals])
        elif kind == "nominal":
            lut = {lvl: i for i, lvl in enumerate(levels)}
            cols[name] = np.asarray(
                [lut.get(v, -1) for v in vals], dtype=np.int32)
            cats.append(name)
            domains[name] = levels
        else:
            cols[name] = np.asarray(
                [None if v == "?" else v for v in vals], dtype=object)
            strs.append(name)
    return Frame.from_numpy(cols, categorical=cats, domains=domains,
                            strings=strs, key=key)
