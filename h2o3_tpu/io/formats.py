"""Additional ingest formats — SVMLight and ARFF.

Reference: water/parser/SVMLightParser.java and ARFFParser.java (both
built-in parser types next to CSV; water/parser/ParseSetup.java
auto-detects them from content). Both decode on the host into dense
columns — the reference likewise densifies SVMLight into a Frame whose
trailing columns are zero-filled.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu.frame.frame import Frame


def parse_svmlight(text: str, key: Optional[str] = None) -> Frame:
    """``label idx:val idx:val …`` lines → dense Frame with a C0
    target column (1-based feature indices, reference SVMLightParser)."""
    labels: List[float] = []
    rows: List[Dict[int, float]] = []
    max_idx = 0
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split()
        labels.append(float(parts[0]))
        row: Dict[int, float] = {}
        for tok in parts[1:]:
            if tok.startswith("qid:"):
                continue
            idx, val = tok.split(":", 1)
            i = int(idx)
            if i < 1:
                raise ValueError(f"SVMLight indices are 1-based, got {i}")
            row[i] = float(val)
            max_idx = max(max_idx, i)
        rows.append(row)
    n = len(rows)
    dense = np.zeros((n, max_idx), dtype=np.float64)
    for r, row in enumerate(rows):
        for i, v in row.items():
            dense[r, i - 1] = v
    cols = {"C0": np.asarray(labels)}
    for j in range(max_idx):
        cols[f"C{j + 1}"] = dense[:, j]
    return Frame.from_numpy(cols, key=key)


_ARFF_ATTR = re.compile(r"@attribute\s+('[^']+'|\S+)\s+(.+)", re.IGNORECASE)


def _split_arff_row(line: str) -> List[str]:
    """Comma split honoring single-quoted values (reference ARFFParser
    quoting rules) — `5.1,'a, b',x` → three fields."""
    out, cur, q = [], [], False
    for ch in line:
        if ch == "'":
            q = not q
            cur.append(ch)
        elif ch == "," and not q:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


def parse_arff(text: str, key: Optional[str] = None) -> Frame:
    """ARFF (@relation/@attribute/@data) → Frame with nominal attributes
    interned as categoricals (reference ARFFParser)."""
    names: List[str] = []
    kinds: List[Tuple[str, Optional[List[str]]]] = []  # (numeric|nominal|string, levels)
    data_lines: List[str] = []
    in_data = False
    for line in text.splitlines():
        s = line.strip()
        if not s or s.startswith("%"):
            continue
        low = s.lower()
        if in_data:
            data_lines.append(s)
            continue
        if low.startswith("@relation"):
            continue
        if low.startswith("@attribute"):
            m = _ARFF_ATTR.match(s)
            if not m:
                raise ValueError(f"bad @attribute line: {s}")
            name = m.group(1).strip("'")
            spec = m.group(2).strip()
            if spec.startswith("{"):
                levels = [v.strip().strip("'") for v in
                          spec.strip("{}").split(",")]
                kinds.append(("nominal", levels))
            elif spec.lower() in ("numeric", "real", "integer"):
                kinds.append(("numeric", None))
            elif spec.lower() == "string":
                kinds.append(("string", None))
            else:   # date etc → treat as string
                kinds.append(("string", None))
            names.append(name)
            continue
        if low.startswith("@data"):
            in_data = True
    if not in_data:
        raise ValueError("no @data section")

    n = len(data_lines)
    cols: Dict[str, np.ndarray] = {}
    raw = [_split_arff_row(ln) for ln in data_lines]
    cats: List[str] = []
    strs: List[str] = []
    domains: Dict[str, List[str]] = {}
    for j, (name, (kind, levels)) in enumerate(zip(names, kinds)):
        vals = [r[j].strip().strip("'") if j < len(r) else "?" for r in raw]
        if kind == "numeric":
            cols[name] = np.asarray(
                [np.nan if v == "?" else float(v) for v in vals])
        elif kind == "nominal":
            lut = {lvl: i for i, lvl in enumerate(levels)}
            cols[name] = np.asarray(
                [lut.get(v, -1) for v in vals], dtype=np.int32)
            cats.append(name)
            domains[name] = levels
        else:
            cols[name] = np.asarray(
                [None if v == "?" else v for v in vals], dtype=object)
            strs.append(name)
    return Frame.from_numpy(cols, categorical=cats, domains=domains,
                            strings=strs, key=key)


def parse_xlsx(path: str, key: Optional[str] = None) -> Frame:
    """XLSX ingest via the stdlib (zipfile + ElementTree) — the
    spreadsheet parser slot of the reference (water/parser/XlsParser.java;
    the modern OOXML container replaces the legacy BIFF stream, which is
    gated off in this build — no xlrd in the image).

    First worksheet only; row 1 becomes the header when every cell in it
    is text, else columns are named C1..Cn (ParseSetup header-guess
    rule). Text columns intern as categoricals like the CSV path.
    """
    import xml.etree.ElementTree as ET
    import zipfile

    NS = "{http://schemas.openxmlformats.org/spreadsheetml/2006/main}"
    with zipfile.ZipFile(path) as z:
        shared: List[str] = []
        if "xl/sharedStrings.xml" in z.namelist():
            root = ET.fromstring(z.read("xl/sharedStrings.xml"))
            for si in root.findall(f"{NS}si"):
                shared.append("".join(t.text or ""
                                      for t in si.iter(f"{NS}t")))
        sheet_names = sorted(n for n in z.namelist()
                             if re.fullmatch(r"xl/worksheets/sheet\d+\.xml", n))
        if not sheet_names:
            raise ValueError(f"{path}: no worksheets found")
        root = ET.fromstring(z.read(sheet_names[0]))

    def col_index(ref: str) -> int:
        j = 0
        for ch in ref:
            if ch.isalpha():
                j = j * 26 + (ord(ch.upper()) - ord("A") + 1)
            else:
                break
        return j - 1

    rows: List[Dict[int, object]] = []
    ncols = 0
    for row_el in root.iter(f"{NS}row"):
        row: Dict[int, object] = {}
        for ci, c in enumerate(row_el.findall(f"{NS}c")):
            ref = c.get("r")
            j = col_index(ref) if ref else ci
            t = c.get("t")
            v_el = c.find(f"{NS}v")
            if t == "inlineStr":
                is_el = c.find(f"{NS}is")
                val = "".join(tt.text or "" for tt in is_el.iter(f"{NS}t")) \
                    if is_el is not None else None
            elif v_el is None or v_el.text is None:
                val = None
            elif t == "s":
                val = shared[int(v_el.text)]
            elif t == "b":
                val = float(int(v_el.text))
            elif t in ("str", "e"):
                val = v_el.text
            else:
                val = float(v_el.text)
            if val is not None:
                row[j] = val
                ncols = max(ncols, j + 1)
        rows.append(row)
    # trim TRAILING styled-but-empty rows only (Excel writers emit them
    # below the data); interior blank rows stay as all-NA rows, matching
    # pandas.read_excel row alignment
    while rows and not rows[-1]:
        rows.pop()
    if not rows or ncols == 0:
        raise ValueError(f"{path}: empty worksheet")

    header = rows[0]
    has_header = (len(header) == ncols
                  and all(isinstance(v, str) for v in header.values()))
    names = ([str(header[j]) for j in range(ncols)] if has_header
             else [f"C{j + 1}" for j in range(ncols)])
    body = rows[1:] if has_header else rows
    cols: Dict[str, np.ndarray] = {}
    cats: List[str] = []
    for j, name in enumerate(names):
        vals = [r.get(j) for r in body]
        if all(v is None or isinstance(v, float) for v in vals):
            cols[name] = np.asarray(
                [np.nan if v is None else v for v in vals], dtype=np.float64)
        else:
            cols[name] = np.asarray(
                [None if v is None else str(v) for v in vals], dtype=object)
            cats.append(name)
    return Frame.from_numpy(cols, categorical=cats, key=key)
