"""Additional ingest formats — SVMLight and ARFF.

Reference: water/parser/SVMLightParser.java and ARFFParser.java (both
built-in parser types next to CSV; water/parser/ParseSetup.java
auto-detects them from content). Both decode on the host into dense
columns — the reference likewise densifies SVMLight into a Frame whose
trailing columns are zero-filled.
"""

from __future__ import annotations

import re
import struct as _struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu.frame.frame import Frame


def parse_svmlight(text: str, key: Optional[str] = None) -> Frame:
    """``label idx:val idx:val …`` lines → dense Frame with a C0
    target column (1-based feature indices, reference SVMLightParser)."""
    labels: List[float] = []
    rows: List[Dict[int, float]] = []
    max_idx = 0
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split()
        labels.append(float(parts[0]))
        row: Dict[int, float] = {}
        for tok in parts[1:]:
            if tok.startswith("qid:"):
                continue
            idx, val = tok.split(":", 1)
            i = int(idx)
            if i < 1:
                raise ValueError(f"SVMLight indices are 1-based, got {i}")
            row[i] = float(val)
            max_idx = max(max_idx, i)
        rows.append(row)
    n = len(rows)
    dense = np.zeros((n, max_idx), dtype=np.float64)
    for r, row in enumerate(rows):
        for i, v in row.items():
            dense[r, i - 1] = v
    cols = {"C0": np.asarray(labels)}
    for j in range(max_idx):
        cols[f"C{j + 1}"] = dense[:, j]
    return Frame.from_numpy(cols, key=key)


_ARFF_ATTR = re.compile(r"@attribute\s+('[^']+'|\S+)\s+(.+)", re.IGNORECASE)


def _split_arff_row(line: str) -> List[str]:
    """Comma split honoring single-quoted values (reference ARFFParser
    quoting rules) — `5.1,'a, b',x` → three fields."""
    out, cur, q = [], [], False
    for ch in line:
        if ch == "'":
            q = not q
            cur.append(ch)
        elif ch == "," and not q:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


def parse_arff(text: str, key: Optional[str] = None) -> Frame:
    """ARFF (@relation/@attribute/@data) → Frame with nominal attributes
    interned as categoricals (reference ARFFParser)."""
    names: List[str] = []
    kinds: List[Tuple[str, Optional[List[str]]]] = []  # (numeric|nominal|string, levels)
    data_lines: List[str] = []
    in_data = False
    for line in text.splitlines():
        s = line.strip()
        if not s or s.startswith("%"):
            continue
        low = s.lower()
        if in_data:
            data_lines.append(s)
            continue
        if low.startswith("@relation"):
            continue
        if low.startswith("@attribute"):
            m = _ARFF_ATTR.match(s)
            if not m:
                raise ValueError(f"bad @attribute line: {s}")
            name = m.group(1).strip("'")
            spec = m.group(2).strip()
            if spec.startswith("{"):
                levels = [v.strip().strip("'") for v in
                          spec.strip("{}").split(",")]
                kinds.append(("nominal", levels))
            elif spec.lower() in ("numeric", "real", "integer"):
                kinds.append(("numeric", None))
            elif spec.lower() == "string":
                kinds.append(("string", None))
            else:   # date etc → treat as string
                kinds.append(("string", None))
            names.append(name)
            continue
        if low.startswith("@data"):
            in_data = True
    if not in_data:
        raise ValueError("no @data section")

    n = len(data_lines)
    cols: Dict[str, np.ndarray] = {}
    raw = [_split_arff_row(ln) for ln in data_lines]
    cats: List[str] = []
    strs: List[str] = []
    domains: Dict[str, List[str]] = {}
    for j, (name, (kind, levels)) in enumerate(zip(names, kinds)):
        vals = [r[j].strip().strip("'") if j < len(r) else "?" for r in raw]
        if kind == "numeric":
            cols[name] = np.asarray(
                [np.nan if v == "?" else float(v) for v in vals])
        elif kind == "nominal":
            lut = {lvl: i for i, lvl in enumerate(levels)}
            cols[name] = np.asarray(
                [lut.get(v, -1) for v in vals], dtype=np.int32)
            cats.append(name)
            domains[name] = levels
        else:
            cols[name] = np.asarray(
                [None if v == "?" else v for v in vals], dtype=object)
            strs.append(name)
    return Frame.from_numpy(cols, categorical=cats, domains=domains,
                            strings=strs, key=key)


def parse_xlsx(path: str, key: Optional[str] = None) -> Frame:
    """XLSX ingest via the stdlib (zipfile + ElementTree) — the
    spreadsheet parser slot of the reference (water/parser/XlsParser.java;
    the modern OOXML container replaces the legacy BIFF stream, which is
    gated off in this build — no xlrd in the image).

    First worksheet only; row 1 becomes the header when every cell in it
    is text, else columns are named C1..Cn (ParseSetup header-guess
    rule). Text columns intern as categoricals like the CSV path.
    """
    import xml.etree.ElementTree as ET
    import zipfile

    NS = "{http://schemas.openxmlformats.org/spreadsheetml/2006/main}"
    with zipfile.ZipFile(path) as z:
        shared: List[str] = []
        if "xl/sharedStrings.xml" in z.namelist():
            root = ET.fromstring(z.read("xl/sharedStrings.xml"))
            for si in root.findall(f"{NS}si"):
                shared.append("".join(t.text or ""
                                      for t in si.iter(f"{NS}t")))
        sheet_names = sorted(n for n in z.namelist()
                             if re.fullmatch(r"xl/worksheets/sheet\d+\.xml", n))
        if not sheet_names:
            raise ValueError(f"{path}: no worksheets found")
        root = ET.fromstring(z.read(sheet_names[0]))

    def col_index(ref: str) -> int:
        j = 0
        for ch in ref:
            if ch.isalpha():
                j = j * 26 + (ord(ch.upper()) - ord("A") + 1)
            else:
                break
        return j - 1

    rows: List[Dict[int, object]] = []
    ncols = 0
    for row_el in root.iter(f"{NS}row"):
        row: Dict[int, object] = {}
        for ci, c in enumerate(row_el.findall(f"{NS}c")):
            ref = c.get("r")
            j = col_index(ref) if ref else ci
            t = c.get("t")
            v_el = c.find(f"{NS}v")
            if t == "inlineStr":
                is_el = c.find(f"{NS}is")
                val = "".join(tt.text or "" for tt in is_el.iter(f"{NS}t")) \
                    if is_el is not None else None
            elif v_el is None or v_el.text is None:
                val = None
            elif t == "s":
                val = shared[int(v_el.text)]
            elif t == "b":
                val = float(int(v_el.text))
            elif t in ("str", "e"):
                val = v_el.text
            else:
                val = float(v_el.text)
            if val is not None:
                row[j] = val
                ncols = max(ncols, j + 1)
        rows.append(row)
    # trim TRAILING styled-but-empty rows only (Excel writers emit them
    # below the data); interior blank rows stay as all-NA rows, matching
    # pandas.read_excel row alignment
    while rows and not rows[-1]:
        rows.pop()
    if not rows or ncols == 0:
        raise ValueError(f"{path}: empty worksheet")

    header = rows[0]
    has_header = (len(header) == ncols
                  and all(isinstance(v, str) for v in header.values()))
    names = ([str(header[j]) for j in range(ncols)] if has_header
             else [f"C{j + 1}" for j in range(ncols)])
    body = rows[1:] if has_header else rows
    cols: Dict[str, np.ndarray] = {}
    cats: List[str] = []
    for j, name in enumerate(names):
        vals = [r.get(j) for r in body]
        if all(v is None or isinstance(v, float) for v in vals):
            cols[name] = np.asarray(
                [np.nan if v is None else v for v in vals], dtype=np.float64)
        else:
            cols[name] = np.asarray(
                [None if v is None else str(v) for v in vals], dtype=object)
            cats.append(name)
    return Frame.from_numpy(cols, categorical=cats, key=key)


# ---- columnar container formats (h2o-parsers/{parquet,orc,avro}) -----
#
# Arrow tables skip the CSV tokenizer entirely: each table (or Parquet
# row group) converts per-column into the SAME merge entries the
# chunk-parallel CSV pipeline produces — categorical code blocks with
# window-local domains, or pre-narrowed NumericBlocks — and feeds the
# same BlockAccumulators (frame/column.py). Buffers that already match
# their narrow dtype ship zero-copy to device_put.

_BOOL_DOMAIN = ["false", "true"]     # matches the CSV tokenizer's levels


def _arrow_entries(table):
    """Per-column merge entries for one Arrow table / row group:
    ('cat', int32 codes with -1 NA, local domain) or
    ('num'|'time', NumericBlock)."""
    import pyarrow as pa
    from h2o3_tpu.frame.column import narrow_numeric_block
    entries = []
    for col in table.columns:
        col = col.combine_chunks() if col.num_chunks != 1 else col.chunk(0)
        t = col.type
        if pa.types.is_dictionary(t):
            idx = col.indices.fill_null(-1).to_numpy(
                zero_copy_only=False).astype(np.int32, copy=False)
            entries.append(
                ("cat", idx, [str(v) for v in col.dictionary.to_pylist()]))
        elif (pa.types.is_string(t) or pa.types.is_large_string(t)
              or pa.types.is_binary(t)):
            if pa.types.is_binary(t):
                col = col.cast(pa.string())   # utf-8 labels, not b'..' reprs
            enc = col.dictionary_encode()     # Arrow-native interning
            idx = enc.indices.fill_null(-1).to_numpy(
                zero_copy_only=False).astype(np.int32, copy=False)
            entries.append(
                ("cat", idx, [str(v) for v in enc.dictionary.to_pylist()]))
        elif pa.types.is_boolean(t):
            # bools are two-level categoricals, like the CSV tokenizer
            # makes of "true"/"false" tokens — an export→re-import
            # round trip keeps the type
            v = col.to_numpy(zero_copy_only=False)
            if col.null_count:
                codes = np.where(np.asarray(col.is_null()), -1,
                                 np.where(v.astype(bool), 1, 0))
                codes = codes.astype(np.int32)
            else:
                codes = v.astype(np.int32)
            entries.append(("cat", codes, _BOOL_DOMAIN))
        elif pa.types.is_timestamp(t) or pa.types.is_date(t):
            # repo time convention is epoch-MILLIS (frame/column.py):
            # normalize whatever unit the container carries
            v = col.cast(pa.int64()).to_numpy(zero_copy_only=False)
            v = v.astype(np.float64)
            if pa.types.is_timestamp(t):
                scale = {"s": 1e3, "ms": 1.0, "us": 1e-3,
                         "ns": 1e-6}[t.unit]
            elif pa.types.is_date32(t):
                scale = 86400e3                   # days → millis
            else:
                scale = 1.0                       # date64 is millis
            v = v * scale
            if col.null_count:
                v[np.asarray(col.is_null())] = np.nan
            entries.append(("time", narrow_numeric_block(v)))
        else:
            if col.null_count:
                # pyarrow null-fills to float64 NaN; mask from finiteness
                v = col.to_numpy(zero_copy_only=False)
                v = v.astype(np.float64, copy=False)
                v[np.asarray(col.is_null())] = np.nan
                entries.append(("num", narrow_numeric_block(v)))
            elif pa.types.is_integer(t):
                # null-free integers can't hold NA: the primitive buffer
                # views zero-copy and, when it already matches its narrow
                # dtype, ships to device without any host copy
                v = col.to_numpy(zero_copy_only=False)
                entries.append(("num", narrow_numeric_block(
                    v, na=np.zeros(len(v), bool))))
            else:
                # null-free floats may still carry NaN payloads → the
                # finiteness-derived mask (CSV-path semantics)
                v = col.to_numpy(zero_copy_only=False)
                entries.append(("num", narrow_numeric_block(
                    np.asarray(v, np.float64))))
    return entries


def _arrow_accumulators(schema):
    """Name → BlockAccumulator for an Arrow schema (T_TIME flagged from
    the schema so every row group agrees)."""
    import pyarrow as pa
    from h2o3_tpu.frame.column import BlockAccumulator
    return {f.name: BlockAccumulator(
                f.name, time=pa.types.is_timestamp(f.type) or
                pa.types.is_date(f.type))
            for f in schema}


def _merge_arrow(accs, names, table) -> int:
    """Feed one table's entries into the accumulators, in column order;
    returns the table's row count."""
    for nm, entry in zip(names, _arrow_entries(table)):
        if entry[0] == "cat":
            accs[nm].add_categorical(entry[1], entry[2])
        else:
            accs[nm].add_numeric_block(entry[1])
    return table.num_rows


def frame_from_arrow(table, key: Optional[str] = None) -> Frame:
    """Arrow table → Frame without a pandas detour (the h2o-parsers
    ParquetParser/OrcParser role): numeric columns become dtype-narrowed
    device arrays + NA masks, string/dictionary/bool columns intern into
    categorical domains, timestamps/dates become T_TIME epoch-millis."""
    names = list(table.column_names)
    accs = _arrow_accumulators(table.schema)
    n = _merge_arrow(accs, names, table)
    return Frame.from_blocks(accs, names, n, key=key, block=8)


def parse_parquet(path: str, key: Optional[str] = None,
                  workers: Optional[int] = None) -> Frame:
    """Row-group-parallel Parquet ingest — the Arrow-native fast path.

    Row groups are read concurrently on the tokenizer-pool knob
    (`H2O3TPU_PARSE_WORKERS`; workers=1 reads sequentially) with each
    worker holding its own ParquetFile handle; the caller thread merges
    groups strictly in order into the shared BlockAccumulators, so the
    parallel read is bit-identical to the sequential one. At most
    workers+2 row groups are resident on the host (memory-governor
    contract), and each group passes a cancel_point.
    """
    import collections as _collections
    import time as _time
    from concurrent.futures import ThreadPoolExecutor
    import pyarrow.parquet as pq
    from h2o3_tpu import telemetry
    from h2o3_tpu.core.request_ctx import cancel_point
    from h2o3_tpu.io import chunking

    w = chunking.resolve_workers(workers)
    pf = pq.ParquetFile(path)
    ng = pf.metadata.num_row_groups
    try:
        import os as _os
        telemetry.counter("ingest_bytes_total", format="parquet").inc(
            _os.path.getsize(path))
    except OSError:
        pass
    if ng == 0:
        fr = frame_from_arrow(pf.read(), key=key)
        telemetry.counter("ingest_rows_total").inc(fr.nrows)
        return fr

    names = [f.name for f in pf.schema_arrow]
    accs = _arrow_accumulators(pf.schema_arrow)
    total = 0

    def _hist(**labels):
        return telemetry.histogram("parse_chunk_seconds", **labels)

    def _read_group(i: int):
        # one ParquetFile handle per read: pyarrow readers are not
        # guaranteed thread-safe for concurrent row-group reads
        t0 = _time.perf_counter()
        tbl = pq.ParquetFile(path).read_row_group(i)
        return tbl, _time.perf_counter() - t0

    def _consume(tbl, read_s: float):
        nonlocal total
        cancel_point("parse.row_group")
        _hist(stage="tokenize").observe(read_s)
        t0 = _time.perf_counter()
        total += _merge_arrow(accs, names, tbl)
        _hist(stage="merge").observe(_time.perf_counter() - t0)

    with telemetry.span("parse.arrow", format="parquet", row_groups=ng,
                        workers=w):
        if w == 1 or ng == 1:
            for i in range(ng):
                _consume(*_read_group(i))
        else:
            futs = _collections.deque()
            with ThreadPoolExecutor(
                    max_workers=min(w, ng),
                    thread_name_prefix="parse-rg") as pool:
                for i in range(ng):
                    futs.append(pool.submit(_read_group, i))
                    # sliding window: bounds resident row groups
                    while len(futs) > w + 2:
                        _consume(*futs.popleft().result())
                while futs:
                    _consume(*futs.popleft().result())
        t0 = _time.perf_counter()
        fr = Frame.from_blocks(accs, names, total, key=key, block=8)
        _hist(stage="transfer").observe(_time.perf_counter() - t0)
    telemetry.counter("ingest_rows_total").inc(fr.nrows)
    return fr


def parse_orc(path: str, key: Optional[str] = None) -> Frame:
    import pyarrow.orc as po
    return frame_from_arrow(po.ORCFile(path).read(), key=key)


# ---- Avro object-container reader (h2o-parsers/h2o-avro-parser) ------


def _avro_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    """zigzag-encoded long."""
    shift = 0
    acc = 0
    while True:
        b = buf[pos]
        pos += 1
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1), pos


def _avro_read(buf: bytes, pos: int, schema):
    """Decode one value of ``schema`` (JSON-decoded avro schema)."""
    if isinstance(schema, list):                 # union: long index first
        idx, pos = _avro_varint(buf, pos)
        return _avro_read(buf, pos, schema[idx])
    if isinstance(schema, dict):
        st = schema["type"]
        if st == "record":
            out = {}
            for f in schema["fields"]:
                out[f["name"]], pos = _avro_read(buf, pos, f["type"])
            return out, pos
        if st == "enum":
            i, pos = _avro_varint(buf, pos)
            return schema["symbols"][i], pos
        if st == "array":
            items = []
            while True:
                n, pos = _avro_varint(buf, pos)
                if n == 0:
                    break
                if n < 0:
                    _, pos = _avro_varint(buf, pos)   # block byte size
                    n = -n
                for _ in range(n):
                    v, pos = _avro_read(buf, pos, schema["items"])
                    items.append(v)
            return items, pos
        return _avro_read(buf, pos, st)
    if schema == "null":
        return None, pos
    if schema == "boolean":
        return buf[pos] != 0, pos + 1
    if schema in ("int", "long"):
        return _avro_varint(buf, pos)
    if schema == "float":
        return _struct.unpack_from("<f", buf, pos)[0], pos + 4
    if schema == "double":
        return _struct.unpack_from("<d", buf, pos)[0], pos + 8
    if schema in ("bytes", "string"):
        n, pos = _avro_varint(buf, pos)
        raw = buf[pos:pos + n]
        return (raw.decode("utf-8", "replace") if schema == "string"
                else raw), pos + n
    raise ValueError(f"unsupported avro type {schema!r}")


def parse_avro(path: str, key: Optional[str] = None) -> Frame:
    """Avro object-container file → Frame (flat record schemas;
    null/deflate codecs) — the h2o-avro-parser role, stdlib-only."""
    import json
    import zlib
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != b"Obj\x01":
        raise ValueError(f"{path} is not an avro container file")
    pos = 4
    meta = {}
    while True:
        n, pos = _avro_varint(data, pos)
        if n == 0:
            break
        if n < 0:
            _, pos = _avro_varint(data, pos)
            n = -n
        for _ in range(n):
            klen, pos = _avro_varint(data, pos)
            k = data[pos:pos + klen].decode()
            pos += klen
            vlen, pos = _avro_varint(data, pos)
            meta[k] = data[pos:pos + vlen]
            pos += vlen
    sync = data[pos:pos + 16]
    pos += 16
    schema = json.loads(meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode()
    if schema.get("type") != "record":
        raise ValueError("only flat record avro schemas are supported")

    def _flat(ft) -> bool:
        if isinstance(ft, list):
            return all(_flat(x) for x in ft)
        if isinstance(ft, dict):
            return ft.get("type") == "enum"
        return ft in ("null", "boolean", "int", "long", "float",
                      "double", "bytes", "string")

    bad = [f["name"] for f in schema["fields"] if not _flat(f["type"])]
    if bad:
        # loud error beats silently-NaN columns for nested/array fields
        raise ValueError("avro fields with nested/array types are not "
                         f"supported: {bad}")
    records: List[dict] = []
    while pos < len(data):
        cnt, pos = _avro_varint(data, pos)
        size, pos = _avro_varint(data, pos)
        block = data[pos:pos + size]
        pos += size
        if data[pos:pos + 16] != sync:
            raise ValueError("avro sync marker mismatch")
        pos += 16
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        elif codec != "null":
            raise ValueError(f"unsupported avro codec '{codec}'")
        bpos = 0
        for _ in range(cnt):
            rec, bpos = _avro_read(block, bpos, schema)
            records.append(rec)
    names = [f["name"] for f in schema["fields"]]
    arrays, cats, doms = {}, [], {}
    for name in names:
        vals = [r.get(name) for r in records]
        non_null = [v for v in vals if v is not None]
        if non_null and all(isinstance(v, (str, bytes)) for v in non_null):
            def _s(v):
                return (v.decode("utf-8", "replace")
                        if isinstance(v, bytes) else str(v))
            levels = sorted({_s(v) for v in non_null})
            lut = {v: i for i, v in enumerate(levels)}
            arrays[name] = np.array(
                [lut.get(_s(v), -1) if v is not None else -1
                 for v in vals], np.int32)
            cats.append(name)
            doms[name] = levels
        else:
            arrays[name] = np.array(
                [float(v) if isinstance(v, (int, float, bool)) else np.nan
                 for v in vals], np.float64)
    return Frame.from_numpy(arrays, categorical=cats, domains=doms,
                            key=key)
