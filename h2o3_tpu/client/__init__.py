"""h2o-py-compatible client — the `import h2o` surface over REST.

Reference: h2o-py (~156K LoC): h2o.init/connect (h2o-py/h2o/h2o.py:49,
138), H2OFrame as a lazy server-side object addressed by key
(h2o-py/h2o/frame.py), and one estimator class per algorithm GENERATED
from REST schema metadata by h2o-bindings/bin/gen_python.py.

Same architecture here, compressed: `connect()` attaches to (or starts)
a server; `H2OFrame` proxies a server-side frame; estimator classes are
generated at connect time from GET /3/ModelBuilders metadata — the
gen_python.py codegen step executed live instead of checked in. Usage:

    from h2o3_tpu import client as h2o
    h2o.init()
    fr = h2o.import_file("data.csv")
    m = h2o.estimators.H2OGradientBoostingEstimator(ntrees=20)
    m.train(y="target", training_frame=fr)
    m.predict(fr)
"""

from __future__ import annotations

import json
import time
import types
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

_conn: Optional["H2OConnection"] = None
import itertools as _it
_expr_counter = _it.count()


class H2OConnection:
    """REST transport (h2o-py/h2o/backend/connection.py role)."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")

    def request(self, method: str, urlpath: str, **params) -> dict:
        url = f"{self.url}{urlpath}"
        data = None
        if method == "POST":
            data = urllib.parse.urlencode(
                {k: (json.dumps(v) if isinstance(v, (list, dict)) else v)
                 for k, v in params.items() if v is not None}).encode()
        elif params:
            url += "?" + urllib.parse.urlencode(
                {k: v for k, v in params.items() if v is not None})
        req = urllib.request.Request(url, data=data, method=method)
        if data:
            req.add_header("Content-Type",
                           "application/x-www-form-urlencoded")
        with urllib.request.urlopen(req, timeout=600) as resp:
            return json.loads(resp.read())

    def wait_job(self, key: str, timeout: float = 3600) -> dict:
        """Poll GET /3/Jobs/{key} (the h2o-py progress loop)."""
        t0 = time.time()
        while time.time() - t0 < timeout:
            j = self.request("GET", f"/3/Jobs/{key}")["jobs"][0]
            if j["status"] == "FAILED":
                raise RuntimeError(j.get("exception") or "job failed")
            if j["status"] == "CANCELLED":
                raise RuntimeError(f"job {key} was cancelled")
            if j["status"] == "DONE":
                return j
            time.sleep(0.2)
        raise TimeoutError(key)


def connection() -> H2OConnection:
    if _conn is None:
        raise RuntimeError("no connection — call h2o.init() / h2o.connect()")
    return _conn


def connect(url: str = "http://127.0.0.1:54321") -> H2OConnection:
    """Attach to a running server (h2o.connect)."""
    global _conn
    _conn = H2OConnection(url)
    _conn.request("GET", "/3/Cloud")
    _generate_estimators()
    return _conn


def init(url: Optional[str] = None, start_local: bool = True,
         port: int = 0) -> H2OConnection:
    """h2o.init: attach, or boot an in-process cloud + server."""
    if url is None and start_local:
        import h2o3_tpu
        from h2o3_tpu.api.server import start_server
        h2o3_tpu.init()
        actual = start_server(port=port, background=True)
        url = f"http://127.0.0.1:{actual}"
    if url is None:
        raise ValueError("init(start_local=False) needs url=<server url>")
    return connect(url)


def cluster_status() -> dict:
    return connection().request("GET", "/3/Cloud")


# ------------------------------------------------------------------ frame

class H2OFrame:
    """Proxy for a server-side frame (h2o-py/h2o/frame.py role —
    operations go through REST/Rapids, data stays on the cluster)."""

    def __init__(self, key: str):
        self.frame_id = key
        self._meta: Optional[dict] = None

    def _info(self) -> dict:
        # frame shape/schema is immutable server-side (mutations produce
        # new keys via Rapids), so cache after one fetch like h2o-py
        if self._meta is None:
            self._meta = connection().request(
                "GET", f"/3/Frames/{self.frame_id}")
        return self._meta

    @property
    def nrows(self) -> int:
        return self._info()["frames"][0]["rows"]

    @property
    def ncols(self) -> int:
        return self._info()["frames"][0]["num_columns"]

    @property
    def names(self) -> List[str]:
        return [c["label"] for c in self._info()["frames"][0]["columns"]]

    @property
    def shape(self):
        f = self._info()["frames"][0]
        return (f["rows"], f["num_columns"])

    def summary(self) -> dict:
        return connection().request(
            "GET", f"/3/Frames/{self.frame_id}/summary")

    def rapids(self, expr: str) -> dict:
        """Ship a Rapids expression (h2o-py/h2o/expr.py ExprNode)."""
        return connection().request("POST", "/99/Rapids", ast=expr)

    def __getitem__(self, col: str) -> "H2OFrame":
        out = self.rapids(f'(cols_py {self.frame_id} "{col}")')
        if "key" not in out:
            raise KeyError(out.get("error")
                           or f"selection '{col}' did not yield a frame")
        return H2OFrame(out["key"]["name"])

    # ---- expression building (h2o-py expr.py ExprNode role): every
    # operator ships a Rapids string; results are new server frames ----
    def _expr(self, op: str, other=None, rev: bool = False) -> "H2OFrame":
        me = self.frame_id
        key = f"py_expr_{next(_expr_counter)}"   # unique per expression
        if other is None:
            ast = f"(tmp= {key} ({op} {me}))"
        else:
            rhs = other.frame_id if isinstance(other, H2OFrame) else repr(
                other) if isinstance(other, str) else str(other)
            a, b = (rhs, me) if rev else (me, rhs)
            ast = f"(tmp= {key} ({op} {a} {b}))"
        out = self.rapids(ast)
        if "key" not in out:
            raise ValueError(out.get("error") or f"rapids op {op} failed")
        return H2OFrame(out["key"]["name"])

    def __add__(self, o): return self._expr("+", o)
    def __radd__(self, o): return self._expr("+", o, rev=True)
    def __sub__(self, o): return self._expr("-", o)
    def __rsub__(self, o): return self._expr("-", o, rev=True)
    def __mul__(self, o): return self._expr("*", o)
    def __rmul__(self, o): return self._expr("*", o, rev=True)
    def __truediv__(self, o): return self._expr("/", o)
    def __rtruediv__(self, o): return self._expr("/", o, rev=True)
    def __lt__(self, o): return self._expr("<", o)
    def __le__(self, o): return self._expr("<=", o)
    def __gt__(self, o): return self._expr(">", o)
    def __ge__(self, o): return self._expr(">=", o)
    def __eq__(self, o):                                # noqa: PLW1641
        if not isinstance(o, (H2OFrame, int, float, str)):
            return NotImplemented
        return self._expr("==", o)

    def __ne__(self, o):
        if not isinstance(o, (H2OFrame, int, float, str)):
            return NotImplemented
        return self._expr("!=", o)
    __hash__ = None   # frames are mutable proxies, not hashable

    def log(self): return self._expr("log")
    def exp(self): return self._expr("exp")
    def sqrt(self): return self._expr("sqrt")
    def abs(self): return self._expr("abs")

    def _scalar(self, op: str) -> float:
        out = self.rapids(f"({op} {self.frame_id} 1)")
        if isinstance(out, dict) and "scalar" in out:
            return out["scalar"]
        raise ValueError(out.get("error") if isinstance(out, dict) else out)

    def mean(self): return self._scalar("mean")
    def sum(self): return self._scalar("sum")
    def min(self): return self._scalar("min")
    def max(self): return self._scalar("max")

    def head(self, rows: int = 10) -> List[dict]:
        """First rows as dicts (fresh fetch honoring row_count)."""
        f = connection().request("GET", f"/3/Frames/{self.frame_id}",
                                 row_count=rows)["frames"][0]
        cols = f["columns"]
        n = min(rows, len(cols[0]["data"]) if cols else 0)
        return [{c["label"]: c["data"][i] for c in cols} for i in range(n)]

    def __repr__(self):
        return f"<H2OFrame {self.frame_id} {self.shape}>"


def _key_name(v) -> str:
    """Key fields arrive as either a bare string or a KeyV3 dict
    ({'name': ..., 'type': ...}) depending on the endpoint."""
    return v["name"] if isinstance(v, dict) else str(v)


def import_file(path: str, destination_frame: Optional[str] = None) -> H2OFrame:
    """h2o.import_file: ImportFiles → ParseSetup → Parse → poll job."""
    c = connection()
    c.request("POST", "/3/ImportFiles", path=path)
    setup = c.request("POST", "/3/ParseSetup", source_frames=[path])
    # h2o-py adopts ParseSetup's suggested destination when none is given
    destination_frame = destination_frame or setup["destination_frame"]
    out = c.request("POST", "/3/Parse", source_frames=[path],
                    destination_frame=destination_frame,
                    separator=setup.get("separator"))
    job = out["job"]
    c.wait_job(_key_name(job["key"]))
    return H2OFrame(_key_name(job["dest"]))


# ------------------------------------------------------------------ model

class H2OModel:
    """Proxy for a trained server-side model."""

    def __init__(self, model_id: str):
        self.model_id = model_id
        self._meta: Optional[dict] = None

    def _info(self) -> dict:
        # trained models are immutable — cache like H2OFrame._info
        if self._meta is None:
            self._meta = connection().request(
                "GET", f"/3/Models/{self.model_id}")
        return self._meta

    @property
    def algo(self) -> str:
        return self._info()["models"][0]["algo"]

    @property
    def params(self) -> dict:
        """actual param values from the ModelSchemaV3 parameters list."""
        plist = self._info()["models"][0].get("parameters") or []
        return {p["name"]: p.get("actual_value") for p in plist}

    def metrics(self, kind: str = "training_metrics") -> dict:
        # metrics live under output (ModelOutputSchemaV3), like the
        # reference wire shape
        return self._info()["models"][0]["output"].get(kind) or {}

    def auc(self) -> float:
        return self.metrics()["AUC"]

    def logloss(self) -> float:
        return self.metrics()["logloss"]

    def _predict_request(self, frame: H2OFrame, **flags) -> H2OFrame:
        out = connection().request(
            "POST",
            f"/3/Predictions/models/{self.model_id}/frames/{frame.frame_id}",
            **flags)
        return H2OFrame(out["predictions_frame"]["name"])

    def predict(self, frame: H2OFrame) -> H2OFrame:
        return self._predict_request(frame)

    def predict_leaf_node_assignment(self, frame: H2OFrame) -> H2OFrame:
        return self._predict_request(frame, leaf_node_assignment="true")

    def predict_contributions(self, frame: H2OFrame) -> H2OFrame:
        """TreeSHAP feature contributions + BiasTerm (h2o-py surface)."""
        return self._predict_request(frame, predict_contributions="true")

    def _download(self, urlpath: str, path: str) -> str:
        req = urllib.request.Request(f"{connection().url}{urlpath}")
        with urllib.request.urlopen(req, timeout=600) as resp:
            payload = resp.read()
        with open(path, "wb") as fh:
            fh.write(payload)
        return path

    def download_mojo(self, path: str) -> str:
        """Fetch the MOJO zip (h2o-py model.download_mojo)."""
        return self._download(f"/3/Models/{self.model_id}/mojo", path)

    def download_pojo(self, path: str) -> str:
        """Fetch the generated-source scorer (h2o-py h2o.download_pojo)."""
        return self._download(f"/3/Models.java/{self.model_id}", path)

    def __repr__(self):
        return f"<H2OModel {self.model_id}>"


class _GeneratedEstimator:
    """Base of runtime-generated estimator classes (the gen_python.py
    codegen output, produced live from /3/ModelBuilders metadata)."""

    algo: str = ""
    _param_names: List[str] = []

    def __init__(self, **params):
        unknown = set(params) - set(self._param_names)
        if unknown:
            raise ValueError(f"unknown {self.algo} params: {sorted(unknown)}")
        self._params = params
        self._model: Optional[H2OModel] = None

    def train(self, x: Optional[List[str]] = None, y: Optional[str] = None,
              training_frame: Optional[H2OFrame] = None,
              validation_frame: Optional[H2OFrame] = None,
              model_id: Optional[str] = None) -> H2OModel:
        """h2o-py argument order: train(x, y, training_frame)."""
        if not isinstance(training_frame, H2OFrame):
            raise ValueError("training_frame must be an H2OFrame "
                             "(h2o-py order is train(x, y, training_frame))")
        c = connection()
        body = dict(self._params)
        body["training_frame"] = training_frame.frame_id
        if y is not None:
            body["response_column"] = y
        if x is not None:
            # the wire contract expresses predictor choice as exclusion;
            # accept h2o-py's str / list-of-str / list-of-int forms
            names = training_frame.names
            if isinstance(x, str):
                x = [x]
            x = [names[i] if isinstance(i, int) else i for i in x]
            unknown = [c for c in x if c not in names]
            if unknown:
                raise ValueError(f"x columns not in frame: {unknown}")
            keep = set(x) | ({y} if y else set())
            body["ignored_columns"] = [n for n in names if n not in keep]
        if validation_frame is not None:
            body["validation_frame"] = validation_frame.frame_id
        if model_id:
            body["model_id"] = model_id
        out = c.request("POST", f"/3/ModelBuilders/{self.algo}", **body)
        job = c.wait_job(_key_name(out["job"]["key"]))
        self._model = H2OModel(_key_name(job["dest"]))
        return self._model

    # delegate everything model-ish to the trained model
    def __getattr__(self, item):
        if self._model is not None:
            return getattr(self._model, item)
        raise AttributeError(item)


# canonical h2o-py class names per algo (gen_python.py naming table)
_CLASS_NAMES = {
    "gbm": "H2OGradientBoostingEstimator",
    "drf": "H2ORandomForestEstimator",
    "glm": "H2OGeneralizedLinearEstimator",
    "deeplearning": "H2ODeepLearningEstimator",
    "kmeans": "H2OKMeansEstimator",
    "pca": "H2OPrincipalComponentAnalysisEstimator",
    "svd": "H2OSingularValueDecompositionEstimator",
    "glrm": "H2OGeneralizedLowRankEstimator",
    "naivebayes": "H2ONaiveBayesEstimator",
    "isolationforest": "H2OIsolationForestEstimator",
    "extendedisolationforest": "H2OExtendedIsolationForestEstimator",
    "upliftdrf": "H2OUpliftRandomForestEstimator",
    "coxph": "H2OCoxProportionalHazardsEstimator",
    "gam": "H2OGeneralizedAdditiveEstimator",
    "rulefit": "H2ORuleFitEstimator",
    "psvm": "H2OSupportVectorMachineEstimator",
    "word2vec": "H2OWord2vecEstimator",
    "isotonicregression": "H2OIsotonicRegressionEstimator",
    "modelselection": "H2OModelSelectionEstimator",
    "anovaglm": "H2OANOVAGLMEstimator",
    "targetencoder": "H2OTargetEncoderEstimator",
    "xgboost": "H2OXGBoostEstimator",
    "aggregator": "H2OAggregatorEstimator",
    "infogram": "H2OInfogram",
    "generic": "H2OGenericEstimator",
}

estimators = types.SimpleNamespace()


def _generate_estimators() -> None:
    """The gen_python.py step: one estimator class per algo, param list
    from the live REST schema metadata."""
    meta = connection().request("GET", "/3/ModelBuilders")["model_builders"]
    for algo, info in meta.items():
        cls_name = _CLASS_NAMES.get(algo, f"H2O{algo.title()}Estimator")
        pnames = [p["name"] for p in info["parameters"]]
        cls = type(cls_name, (_GeneratedEstimator,),
                   {"algo": algo, "_param_names": pnames,
                    "__doc__": f"Generated from /3/ModelBuilders[{algo}]"})
        setattr(estimators, cls_name, cls)


class H2OAutoML:
    """h2o-py H2OAutoML shim (POST /99/AutoMLBuilder + leaderboard)."""

    def __init__(self, max_models: int = 10, max_runtime_secs: float = 0,
                 seed: int = -1, project_name: Optional[str] = None,
                 **kw):
        self.spec = {"max_models": max_models,
                     "max_runtime_secs": max_runtime_secs, "seed": seed,
                     "project_name": project_name or "automl", **kw}
        self.leader: Optional[H2OModel] = None

    def train(self, x: Optional[List[str]] = None, y: str = None,
              training_frame: H2OFrame = None) -> H2OModel:
        if not isinstance(training_frame, H2OFrame):
            raise ValueError("training_frame must be an H2OFrame "
                             "(h2o-py order is train(x, y, training_frame))")
        c = connection()
        build_control = {"project_name": self.spec["project_name"],
                         "stopping_criteria": {
                             "max_models": self.spec["max_models"],
                             "max_runtime_secs": self.spec["max_runtime_secs"],
                             "seed": self.spec["seed"]}}
        if self.spec.get("nfolds") is not None:
            build_control["nfolds"] = self.spec["nfolds"]
        build_models = {k: self.spec[k]
                        for k in ("include_algos", "exclude_algos")
                        if self.spec.get(k) is not None}
        input_spec = {"training_frame": training_frame.frame_id,
                      "response_column": y}
        if x is not None:
            keep = set(x) | {y}
            input_spec["ignored_columns"] = [
                n for n in training_frame.names if n not in keep]
        out = c.request("POST", "/99/AutoMLBuilder",
                        build_control=build_control,
                        input_spec=input_spec,
                        build_models=build_models or None)
        c.wait_job(_key_name(out["job"]["key"]))
        lb = self.leaderboard
        self.leader = H2OModel(lb[0]["model_id"]) if lb else None
        return self.leader

    @property
    def leaderboard(self) -> List[dict]:
        out = connection().request(
            "GET", f"/99/Leaderboards/{self.spec['project_name']}")
        return out.get("leaderboard_table") or [
            {"model_id": k} for k in out.get("models", [])]
