"""MOJO — the portable trained-model artifact format.

Reference: h2o-genmodel MOJO zips (hex/genmodel/MojoModel.java:12,
readers under hex/genmodel/algos/{gbm,drf,glm,deeplearning,kmeans,
isofor}) — a zip of a `model.ini` plus binary blobs, scored offline by a
dependency-free runtime (GenModel.score0, hex/genmodel/GenModel.java:363).

TPU-native redesign: the artifact is a zip of
  - ``meta.json``  — algo, category, feature names/types, response
    domain, per-feature categorical domains, scalar scoring constants
  - ``arrays.npz`` — every numeric blob (tree tensors, bin edges,
    coefficients, layer weights, centroids) as plain numpy arrays
and the offline runtime (readers.py) is numpy-only — no JAX, no device —
so exported models score anywhere a `pip install numpy` exists, the same
portability contract the reference's genmodel jar provides.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Dict, Optional

import numpy as np

MOJO_FORMAT_VERSION = "1.0"


def mojo_bytes(meta: dict, arrays: Dict[str, np.ndarray]) -> bytes:
    """Render a MOJO zip (meta.json + arrays.npz) in memory."""
    meta = dict(meta)
    meta["mojo_version"] = MOJO_FORMAT_VERSION
    npz = io.BytesIO()
    np.savez_compressed(npz, **{k: np.asarray(v) for k, v in arrays.items()})
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", compression=zipfile.ZIP_DEFLATED) as z:
        z.writestr("meta.json", json.dumps(meta, indent=1))
        z.writestr("arrays.npz", npz.getvalue())
    return buf.getvalue()


def write_mojo(path: str, meta: dict, arrays: Dict[str, np.ndarray]) -> str:
    """Write a MOJO zip: meta.json + arrays.npz."""
    with open(path, "wb") as fh:
        fh.write(mojo_bytes(meta, arrays))
    return path


def read_mojo(path: str):
    """Read a MOJO zip → (meta dict, arrays dict)."""
    with zipfile.ZipFile(path, "r") as z:
        meta = json.loads(z.read("meta.json").decode())
        npz = np.load(io.BytesIO(z.read("arrays.npz")), allow_pickle=False)
        arrays = {k: npz[k] for k in npz.files}
    return meta, arrays


# ------------------------------------------------------------------
# shared raw-row → binned/encoded feature plumbing for the readers
# ------------------------------------------------------------------

def encode_columns(meta: dict, data: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Normalize a dict of raw columns to float/object numpy arrays."""
    out = {}
    n = None
    for name in meta["names"]:
        if name not in data:
            raise KeyError(f"missing feature column '{name}'")
        v = np.asarray(data[name])
        if n is None:
            n = len(v)
        out[name] = v
    return out


def bin_raw(meta: dict, arrays: Dict[str, np.ndarray],
            data: Dict[str, np.ndarray]) -> np.ndarray:
    """Bin raw feature columns exactly like frame/binning.py bin_frame.

    Numeric: bin = #(edges <= x); categorical: domain index, with
    ADJACENT codes grouped by integer divide when the training
    cardinality exceeded nbins_cats (the DHistogram grouped cat-bin
    cap); NA / unseen level → bin B-1.
    """
    names = meta["names"]
    B = int(meta["nbins_total"])
    nb = arrays["nbins"].astype(np.int64)
    edges = arrays["edges"]
    is_cat = arrays["is_cat"].astype(bool)
    domains = meta.get("feature_domains") or [None] * len(names)
    cols = encode_columns(meta, data)
    n = len(next(iter(cols.values())))
    bins = np.zeros((n, len(names)), dtype=np.int32)
    for i, name in enumerate(names):
        v = cols[name]
        if is_cat[i]:
            dom = domains[i] or []
            lut = {lvl: j for j, lvl in enumerate(dom)}
            if v.dtype.kind in "fiu":       # already numeric codes? treat as str
                v = v.astype(object).astype(str)
            code = np.array([lut.get(str(x), -1) if x is not None else -1
                             for x in v], dtype=np.int64)
            card = max(len(dom), 1)
            div = -(-card // max(nb[i], 1)) if card > nb[i] else 1
            b = code // div if div > 1 else code
            b = np.where(code < 0, B - 1, b)
        else:
            x = v.astype(np.float64)
            e = edges[i]
            e = e[np.isfinite(e)]
            b = np.sum(x[:, None] >= e[None, :], axis=1).astype(np.int64)
            b = np.where(np.isnan(x), B - 1, b)
        bins[:, i] = b
    return bins


def walk_forest(arrays: Dict[str, np.ndarray], bins: np.ndarray,
                B: int) -> np.ndarray:
    """Route binned rows through every stored tree → [T, N] leaf values.

    The numpy twin of models/tree.py predict_tree (the CompressedTree
    walk, hex/genmodel/algos/tree/SharedTreeMojoModel scoring role).
    """
    feat = arrays["tree_feat"]        # [T, D, L]
    thresh = arrays["tree_thresh"]
    na_left = arrays["tree_na_left"].astype(bool)
    is_split = arrays["tree_is_split"].astype(bool)
    leaf = arrays["tree_leaf"]        # [T, 2^D]
    cat_split = arrays.get("tree_cat_split")
    left_words = arrays.get("tree_left_words")
    T = feat.shape[0]
    out = np.zeros((T, bins.shape[0]), dtype=np.float64)
    for t in range(T):
        nid = route_tree_nids(feat[t], thresh[t], na_left[t], is_split[t],
                              bins, B,
                              None if cat_split is None else cat_split[t],
                              None if left_words is None else left_words[t])
        out[t] = leaf[t][nid]
    return out


def route_tree_nids(feat, thresh, na_left, is_split, bins: np.ndarray,
                    B: int, cat_split=None, left_words=None) -> np.ndarray:
    """Terminal leaf id per row for ONE tree [D, L] (RuleFit rule
    membership is a leaf-id range check — models/rulefit.py _route_nids
    twin on the host). Categorical subset splits test the row's bin bit
    in the node's packed left-set words."""
    D = feat.shape[0]
    n = bins.shape[0]
    nid = np.zeros(n, dtype=np.int64)
    for d in range(D):
        f_r = feat[d][nid]
        t_r = thresh[d][nid]
        nal = na_left[d][nid]
        isp = is_split[d][nid]
        b_r = bins[np.arange(n), f_r]
        isna = b_r == (B - 1)
        go = b_r <= t_r
        if cat_split is not None and cat_split[d].any():
            lw = left_words[d][nid]                     # [n, W]
            W = lw.shape[1]
            widx = np.clip(b_r >> 5, 0, W - 1)
            word = lw[np.arange(n), widx]
            inset = ((word >> (b_r & 31).astype(np.uint32)) & 1) == 1
            go = np.where(cat_split[d][nid], inset, go)
        goleft = np.where(isp, np.where(isna, nal, go), True)
        nid = 2 * nid + np.where(goleft, 0, 1)
    return nid


def walk_forest_pathlen(arrays: Dict[str, np.ndarray], bins: np.ndarray,
                        B: int) -> np.ndarray:
    """IsolationForest walk: path length = #splits traversed + the stored
    leaf correction term (models/isofor.py _tree_path_length twin)."""
    feat = arrays["tree_feat"]
    thresh = arrays["tree_thresh"]
    na_left = arrays["tree_na_left"].astype(bool)
    is_split = arrays["tree_is_split"].astype(bool)
    leaf = arrays["tree_leaf"]
    T, D, _ = feat.shape
    n = bins.shape[0]
    out = np.zeros((T, n), dtype=np.float64)
    for t in range(T):
        nid = np.zeros(n, dtype=np.int64)
        plen = np.zeros(n, dtype=np.float64)
        for d in range(D):
            isp = is_split[t, d][nid]
            plen += isp
            f_r = feat[t, d][nid]
            t_r = thresh[t, d][nid]
            nal = na_left[t, d][nid]
            b_r = bins[np.arange(n), f_r]
            isna = b_r == (B - 1)
            goleft = np.where(isp, np.where(isna, nal, b_r <= t_r), True)
            nid = 2 * nid + np.where(goleft, 0, 1)
        out[t] = plen + leaf[t][nid]
    return out


def design_matrix(meta: dict, arrays: Dict[str, np.ndarray],
                  data: Dict[str, np.ndarray]) -> np.ndarray:
    """Numpy twin of frame/datainfo.py build_datainfo: one-hot expansion
    + mean imputation + optional standardization with TRAINING stats."""
    names = meta["names"]
    domains = meta.get("feature_domains") or [None] * len(names)
    standardize = bool(meta.get("standardize", True))
    use_all = bool(meta.get("use_all_factor_levels", False))
    means = arrays["num_means"]
    sigmas = arrays["num_sigmas"]
    cols = encode_columns(meta, data)
    n = len(next(iter(cols.values())))
    blocks = []
    ni = 0
    for i, name in enumerate(names):
        v = cols[name]
        dom = domains[i]
        if dom is not None:
            lut = {lvl: j for j, lvl in enumerate(dom)}
            if v.dtype.kind in "fiu":
                v = v.astype(object).astype(str)
            code = np.array([lut.get(str(x), -1) if x is not None else -1
                             for x in v], dtype=np.int64)
            first = 0 if use_all else 1
            card = max(len(dom), 1)
            oh = (code[:, None] ==
                  np.arange(first, card)[None, :]).astype(np.float64)
            oh[code < 0] = 0.0
            blocks.append(oh)
        else:
            x = v.astype(np.float64)
            mu = float(means[ni]) if ni < len(means) else 0.0
            sd = float(sigmas[ni]) if ni < len(sigmas) else 1.0
            ni += 1
            x = np.where(np.isnan(x), mu, x)
            if standardize:
                x = (x - mu) / (sd if sd > 0 else 1.0)
            blocks.append(x[:, None])
    return (np.concatenate(blocks, axis=1) if blocks
            else np.zeros((n, 0), dtype=np.float64))
