"""h2o3_tpu.genmodel — standalone offline scoring (the h2o-genmodel twin).

Numpy-only: importable and usable without JAX or any device. See
mojo.py (format), readers.py (per-algo scorers), easy.py (typed wrapper).
"""

from h2o3_tpu.genmodel.easy import EasyPredictModelWrapper  # noqa: F401
from h2o3_tpu.genmodel.mojo import read_mojo, write_mojo     # noqa: F401
from h2o3_tpu.genmodel.readers import MojoModel              # noqa: F401


def load_mojo(path: str) -> MojoModel:
    """Load a MOJO zip for offline scoring (MojoModel.load)."""
    return MojoModel.load(path)
