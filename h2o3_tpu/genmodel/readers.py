"""Offline MOJO scoring runtime — numpy-only, no JAX / no device.

Reference: hex/genmodel/MojoModel.java:12 + per-algo readers under
hex/genmodel/algos/{gbm,drf,glm,deeplearning,kmeans,isofor}; the
scoring contract is GenModel.score0 (hex/genmodel/GenModel.java:363):
raw row in, prediction vector out, with the same categorical-domain and
NA semantics as in-cluster scoring.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from h2o3_tpu.genmodel.mojo import (bin_raw, design_matrix, read_mojo,
                                    walk_forest)


class MojoModel:
    """Loaded offline model (hex/genmodel/MojoModel.java role)."""

    def __init__(self, meta: dict, arrays: Dict[str, np.ndarray]):
        self.meta = meta
        self.arrays = arrays

    # -- introspection -------------------------------------------------
    @property
    def algo(self) -> str:
        return self.meta["algo"]

    @property
    def category(self) -> str:
        return self.meta["category"]

    @property
    def names(self) -> List[str]:
        return self.meta["names"]

    @property
    def domain(self) -> Optional[List[str]]:
        return self.meta.get("domain")

    @property
    def nclasses(self) -> int:
        return int(self.meta.get("nclasses") or 1)

    # -- scoring -------------------------------------------------------
    def predict(self, data: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Batch scoring on dict-of-raw-columns → dict of predictions,
        matching the in-cluster ``model.predict`` column layout."""
        raise NotImplementedError

    def score0(self, row: dict) -> dict:
        """Single-row score (GenModel.score0)."""
        batch = {k: np.asarray([v]) for k, v in row.items()}
        out = self.predict(batch)
        return {k: v[0] for k, v in out.items()}

    # -- loading -------------------------------------------------------
    @staticmethod
    def load(path: str) -> "MojoModel":
        meta, arrays = read_mojo(path)
        cls = _READERS.get(meta["algo"])
        if cls is None:
            raise ValueError(f"no MOJO reader for algo '{meta['algo']}'")
        return cls(meta, arrays)


def _softmax(z):
    z = z - z.max(axis=1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=1, keepdims=True)


def _class_output(p: np.ndarray, threshold: float, domain) -> dict:
    if p.shape[1] == 2:
        lab = (p[:, 1] >= threshold).astype(np.int32)
        return {"predict": lab, "p0": p[:, 0], "p1": p[:, 1]}
    out = {"predict": p.argmax(axis=1).astype(np.int32)}
    for k in range(p.shape[1]):
        out[f"p{k}"] = p[:, k]
    return out


def _link_inv(name: str, eta: np.ndarray, tweedie_power: float = 1.5):
    if name in ("identity", "gaussian", "laplace", "quantile", "huber"):
        return eta
    if name in ("logit", "bernoulli", "binomial", "quasibinomial"):
        return 1.0 / (1.0 + np.exp(-eta))
    if name in ("log", "poisson", "gamma", "tweedie"):
        return np.exp(eta)
    if name == "inverse":
        return 1.0 / np.where(np.abs(eta) < 1e-12, 1e-12, eta)
    return eta


class SharedTreeMojoModel(MojoModel):
    """GBM/DRF/IsolationForest share the stored-forest walk
    (hex/genmodel/algos/tree/SharedTreeMojoModel role)."""

    def _tree_sums(self, data) -> np.ndarray:
        B = int(self.meta["nbins_total"])
        bins = bin_raw(self.meta, self.arrays, data)
        return walk_forest(self.arrays, bins, B)   # [T_total, N]


class GbmMojoModel(SharedTreeMojoModel):
    def predict(self, data):
        per_tree = self._tree_sums(data)
        f0 = np.asarray(self.meta["f0"], dtype=np.float64)
        cat = self.category
        if cat == "Multinomial":
            K = self.nclasses
            T = per_tree.shape[0] // K
            marg = f0[None, :] + per_tree.reshape(T, K, -1).sum(axis=0).T
            return _class_output(_softmax(marg), 0.5, self.domain)
        marg = float(f0) + per_tree.sum(axis=0)
        if cat == "Binomial":
            p1 = 1.0 / (1.0 + np.exp(-marg))
            p = np.stack([1 - p1, p1], axis=1)
            return _class_output(p, self.meta.get("default_threshold", 0.5),
                                 self.domain)
        mu = _link_inv(self.meta.get("distribution", "gaussian"), marg,
                       self.meta.get("tweedie_power", 1.5))
        return {"predict": mu}


class DrfMojoModel(SharedTreeMojoModel):
    def predict(self, data):
        per_tree = self._tree_sums(data)
        cat = self.category
        if cat == "Regression":
            return {"predict": per_tree.mean(axis=0)}
        if cat == "Binomial":
            p1 = np.clip(per_tree.mean(axis=0), 0.0, 1.0)
            p = np.stack([1 - p1, p1], axis=1)
            return _class_output(p, self.meta.get("default_threshold", 0.5),
                                 self.domain)
        K = self.nclasses
        T = per_tree.shape[0] // K
        votes = per_tree.reshape(T, K, -1).mean(axis=0).T   # [N, K]
        votes = np.clip(votes, 0.0, 1.0)
        p = votes / np.maximum(votes.sum(axis=1, keepdims=True), 1e-12)
        return _class_output(p, 0.5, self.domain)


class IsoForMojoModel(SharedTreeMojoModel):
    def predict(self, data):
        from h2o3_tpu.genmodel.mojo import bin_raw, walk_forest_pathlen
        B = int(self.meta["nbins_total"])
        bins = bin_raw(self.meta, self.arrays, data)
        per_tree = walk_forest_pathlen(self.arrays, bins, B)
        ml = per_tree.mean(axis=0)
        c = max(float(self.meta["c_norm"]), 1e-12)
        return {"predict": 2.0 ** (-ml / c), "mean_length": ml}


class GlmMojoModel(MojoModel):
    def predict(self, data):
        X = design_matrix(self.meta, self.arrays, data)
        X1 = np.concatenate([X, np.ones((X.shape[0], 1))], axis=1)
        if "coef_multinomial" in self.arrays:
            eta = X1 @ self.arrays["coef_multinomial"]
            return _class_output(_softmax(eta), 0.5, self.domain)
        eta = X1 @ self.arrays["coef"]
        link = self.meta.get("link", "identity")
        mu = _link_inv(link, eta, self.meta.get("tweedie_power", 1.5))
        if self.category == "Binomial":
            p = np.stack([1 - mu, mu], axis=1)
            return _class_output(p, self.meta.get("default_threshold", 0.5),
                                 self.domain)
        return {"predict": mu}


class DeepLearningMojoModel(MojoModel):
    def _forward(self, X: np.ndarray) -> np.ndarray:
        act = self.meta.get("activation", "rectifier")
        n_layers = int(self.meta["n_layers"])
        h = X
        for i in range(n_layers):
            z = h @ self.arrays[f"W{i}"] + self.arrays[f"b{i}"]
            if i == n_layers - 1:
                return z
            if act == "maxout":
                z = z.reshape(z.shape[0], -1, 2).max(axis=2)
            elif act == "tanh":
                z = np.tanh(z)
            else:
                z = np.maximum(z, 0.0)
            h = z
        return h

    def predict(self, data):
        X = design_matrix(self.meta, self.arrays, data)
        out = self._forward(X)
        cat = self.category
        if self.meta.get("autoencoder"):
            return {"reconstruction_error": np.mean((out - X) ** 2, axis=1)}
        if cat in ("Binomial", "Multinomial"):
            p = _softmax(out)
            return _class_output(p, self.meta.get("default_threshold", 0.5),
                                 self.domain)
        mu, sd = self.meta["resp_stats"]
        return {"predict": out[:, 0] * sd + mu}


class KMeansMojoModel(MojoModel):
    def predict(self, data):
        X = design_matrix(self.meta, self.arrays, data)
        C = self.arrays["centers"]
        d2 = ((X[:, None, :] - C[None, :, :]) ** 2).sum(axis=2)
        return {"predict": d2.argmin(axis=1).astype(np.int32)}


_READERS = {
    "gbm": GbmMojoModel,
    "drf": DrfMojoModel,
    "isolationforest": IsoForMojoModel,
    "glm": GlmMojoModel,
    "deeplearning": DeepLearningMojoModel,
    "kmeans": KMeansMojoModel,
}
