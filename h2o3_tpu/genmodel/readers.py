"""Offline MOJO scoring runtime — numpy-only, no JAX / no device.

Reference: hex/genmodel/MojoModel.java:12 + per-algo readers under
hex/genmodel/algos/{gbm,drf,glm,deeplearning,kmeans,isofor}; the
scoring contract is GenModel.score0 (hex/genmodel/GenModel.java:363):
raw row in, prediction vector out, with the same categorical-domain and
NA semantics as in-cluster scoring.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from h2o3_tpu.genmodel.mojo import (bin_raw, design_matrix, read_mojo,
                                    walk_forest)


class MojoModel:
    """Loaded offline model (hex/genmodel/MojoModel.java role)."""

    def __init__(self, meta: dict, arrays: Dict[str, np.ndarray]):
        self.meta = meta
        self.arrays = arrays

    # -- introspection -------------------------------------------------
    @property
    def algo(self) -> str:
        return self.meta["algo"]

    @property
    def category(self) -> str:
        return self.meta["category"]

    @property
    def names(self) -> List[str]:
        return self.meta["names"]

    @property
    def domain(self) -> Optional[List[str]]:
        return self.meta.get("domain")

    @property
    def nclasses(self) -> int:
        return int(self.meta.get("nclasses") or 1)

    # -- scoring -------------------------------------------------------
    def predict(self, data: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Batch scoring on dict-of-raw-columns → dict of predictions,
        matching the in-cluster ``model.predict`` column layout."""
        raise NotImplementedError

    def score0(self, row: dict) -> dict:
        """Single-row score (GenModel.score0)."""
        batch = {k: np.asarray([v]) for k, v in row.items()}
        out = self.predict(batch)
        return {k: v[0] for k, v in out.items()}

    # -- loading -------------------------------------------------------
    @staticmethod
    def load(path: str) -> "MojoModel":
        meta, arrays = read_mojo(path)
        cls = _READERS.get(meta["algo"])
        if cls is None:
            raise ValueError(f"no MOJO reader for algo '{meta['algo']}'")
        return cls(meta, arrays)


def _softmax(z):
    z = z - z.max(axis=1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=1, keepdims=True)


def _class_output(p: np.ndarray, threshold: float, domain) -> dict:
    if p.shape[1] == 2:
        lab = (p[:, 1] >= threshold).astype(np.int32)
        return {"predict": lab, "p0": p[:, 0], "p1": p[:, 1]}
    out = {"predict": p.argmax(axis=1).astype(np.int32)}
    for k in range(p.shape[1]):
        out[f"p{k}"] = p[:, k]
    return out


def _link_inv(name: str, eta: np.ndarray, tweedie_power: float = 1.5):
    if name in ("identity", "gaussian", "laplace", "quantile", "huber"):
        return eta
    if name in ("logit", "bernoulli", "binomial", "quasibinomial"):
        return 1.0 / (1.0 + np.exp(-eta))
    if name in ("log", "poisson", "gamma", "tweedie"):
        return np.exp(eta)
    if name == "inverse":
        return 1.0 / np.where(np.abs(eta) < 1e-12, 1e-12, eta)
    return eta


class SharedTreeMojoModel(MojoModel):
    """GBM/DRF/IsolationForest share the stored-forest walk
    (hex/genmodel/algos/tree/SharedTreeMojoModel role)."""

    def _tree_sums(self, data) -> np.ndarray:
        B = int(self.meta["nbins_total"])
        bins = bin_raw(self.meta, self.arrays, data)
        return walk_forest(self.arrays, bins, B)   # [T_total, N]

    def predict_contributions(self, data) -> Dict[str, np.ndarray]:
        """Offline TreeSHAP (hex/genmodel/algos/tree/TreeSHAP.java role):
        feature columns + BiasTerm, summing to the raw margin."""
        import types

        from h2o3_tpu.ml.shap import forest_contributions
        cat = self.category
        if cat not in ("Regression", "Binomial"):
            raise ValueError(
                "predict_contributions supports only regression and "
                f"binomial models (got {cat})")
        if "tree_leaf_w" not in self.arrays:
            raise ValueError("MOJO lacks node weights "
                             "(exported before TreeSHAP support)")
        B = int(self.meta["nbins_total"])
        bins = bin_raw(self.meta, self.arrays, data)
        tf = self.arrays["tree_feat"]
        forest = types.SimpleNamespace(
            feat=tf, thresh=self.arrays["tree_thresh"],
            na_left=self.arrays["tree_na_left"],
            is_split=self.arrays["tree_is_split"],
            leaf=self.arrays["tree_leaf"], leaf_w=self.arrays["tree_leaf_w"],
            cat_split=self.arrays.get(
                "tree_cat_split", np.zeros(tf.shape, bool)),
            left_words=self.arrays.get(
                "tree_left_words",
                np.zeros(tf.shape + (1,), np.uint32)))
        T = forest.feat.shape[0]
        scale = 1.0 / T if self.algo == "drf" else 1.0
        phi = forest_contributions(forest, bins, B, scale=scale)
        if self.algo == "gbm":
            phi[:, -1] += float(np.asarray(self.meta["f0"], np.float64))
        out = {n: phi[:, j] for j, n in enumerate(self.names)}
        out["BiasTerm"] = phi[:, -1]
        return out


class GbmMojoModel(SharedTreeMojoModel):
    def predict(self, data):
        per_tree = self._tree_sums(data)
        f0 = np.asarray(self.meta["f0"], dtype=np.float64)
        cat = self.category
        if cat == "Multinomial":
            K = self.nclasses
            T = per_tree.shape[0] // K
            marg = f0[None, :] + per_tree.reshape(T, K, -1).sum(axis=0).T
            return _class_output(_softmax(marg), 0.5, self.domain)
        marg = float(f0) + per_tree.sum(axis=0)
        if cat == "Binomial":
            p1 = 1.0 / (1.0 + np.exp(-marg))
            p = np.stack([1 - p1, p1], axis=1)
            return _class_output(p, self.meta.get("default_threshold", 0.5),
                                 self.domain)
        mu = _link_inv(self.meta.get("distribution", "gaussian"), marg,
                       self.meta.get("tweedie_power", 1.5))
        return {"predict": mu}


class DrfMojoModel(SharedTreeMojoModel):
    def predict(self, data):
        per_tree = self._tree_sums(data)
        cat = self.category
        if cat == "Regression":
            return {"predict": per_tree.mean(axis=0)}
        if cat == "Binomial":
            p1 = np.clip(per_tree.mean(axis=0), 0.0, 1.0)
            p = np.stack([1 - p1, p1], axis=1)
            return _class_output(p, self.meta.get("default_threshold", 0.5),
                                 self.domain)
        K = self.nclasses
        T = per_tree.shape[0] // K
        votes = per_tree.reshape(T, K, -1).mean(axis=0).T   # [N, K]
        votes = np.clip(votes, 0.0, 1.0)
        p = votes / np.maximum(votes.sum(axis=1, keepdims=True), 1e-12)
        return _class_output(p, 0.5, self.domain)


class IsoForMojoModel(SharedTreeMojoModel):
    def predict(self, data):
        from h2o3_tpu.genmodel.mojo import bin_raw, walk_forest_pathlen
        B = int(self.meta["nbins_total"])
        bins = bin_raw(self.meta, self.arrays, data)
        per_tree = walk_forest_pathlen(self.arrays, bins, B)
        ml = per_tree.mean(axis=0)
        mn = self.meta.get("min_path_length")
        mx = self.meta.get("max_path_length")
        if mn is not None and mx is not None and float(mx) > float(mn):
            # reference normalization against the training frame's
            # path-length extrema — the exact math of the in-cluster
            # scorer (models/isofor.py _score_raw)
            ntrees = per_tree.shape[0]
            score = (float(mx) - ml * ntrees) / (float(mx) - float(mn))
        else:
            c = max(float(self.meta["c_norm"]), 1e-12)
            score = 2.0 ** (-ml / c)
        return {"predict": score, "mean_length": ml}


class GlmMojoModel(MojoModel):
    def predict(self, data):
        X = design_matrix(self.meta, self.arrays, data)
        X1 = np.concatenate([X, np.ones((X.shape[0], 1))], axis=1)
        if "coef_multinomial" in self.arrays:
            eta = X1 @ self.arrays["coef_multinomial"]
            return _class_output(_softmax(eta), 0.5, self.domain)
        eta = X1 @ self.arrays["coef"]
        link = self.meta.get("link", "identity")
        mu = _link_inv(link, eta, self.meta.get("tweedie_power", 1.5))
        if self.category == "Binomial":
            p = np.stack([1 - mu, mu], axis=1)
            return _class_output(p, self.meta.get("default_threshold", 0.5),
                                 self.domain)
        return {"predict": mu}


class DeepLearningMojoModel(MojoModel):
    def _forward(self, X: np.ndarray) -> np.ndarray:
        act = self.meta.get("activation", "rectifier")
        n_layers = int(self.meta["n_layers"])
        h = X
        for i in range(n_layers):
            z = h @ self.arrays[f"W{i}"] + self.arrays[f"b{i}"]
            if i == n_layers - 1:
                return z
            if act == "maxout":
                z = z.reshape(z.shape[0], -1, 2).max(axis=2)
            elif act == "tanh":
                z = np.tanh(z)
            else:
                z = np.maximum(z, 0.0)
            h = z
        return h

    def predict(self, data):
        X = design_matrix(self.meta, self.arrays, data)
        out = self._forward(X)
        cat = self.category
        if self.meta.get("autoencoder"):
            return {"reconstruction_error": np.mean((out - X) ** 2, axis=1)}
        if cat in ("Binomial", "Multinomial"):
            p = _softmax(out)
            return _class_output(p, self.meta.get("default_threshold", 0.5),
                                 self.domain)
        mu, sd = self.meta["resp_stats"]
        return {"predict": out[:, 0] * sd + mu}


class KMeansMojoModel(MojoModel):
    def predict(self, data):
        X = design_matrix(self.meta, self.arrays, data)
        C = self.arrays["centers"]
        d2 = ((X[:, None, :] - C[None, :, :]) ** 2).sum(axis=2)
        return {"predict": d2.argmin(axis=1).astype(np.int32)}


class PcaMojoModel(MojoModel):
    def predict(self, data):
        X = design_matrix(self.meta, self.arrays, data)
        if self.algo == "pca":
            scores = X @ self.arrays["eigvecs"]
            return {f"PC{i + 1}": scores[:, i]
                    for i in range(scores.shape[1])}
        proj = X @ self.arrays["v"]
        u = proj / np.maximum(self.arrays["d"][None, :], 1e-12)
        return {f"u{i + 1}": u[:, i] for i in range(u.shape[1])}


class IsotonicMojoModel(MojoModel):
    def predict(self, data):
        x = np.asarray(data[self.names[0]], dtype=np.float64)
        tx, ty = self.arrays["thresholds_x"], self.arrays["thresholds_y"]
        pred = np.interp(np.clip(x, tx[0], tx[-1]), tx, ty)
        pred[np.isnan(x)] = np.nan
        if str(self.meta.get("out_of_bounds", "clip")).lower() == "na":
            pred[(x < tx[0]) | (x > tx[-1])] = np.nan
        return {"predict": pred}


class CoxPHMojoModel(MojoModel):
    def predict(self, data):
        X = design_matrix(self.meta, self.arrays, data)
        lp = X @ self.arrays["coef"] - self.meta["eta_mean"]
        return {"lp": lp}


class NaiveBayesMojoModel(MojoModel):
    def predict(self, data):
        priors = self.arrays["priors"]
        K = len(priors)
        num_names = self.meta["num_names"]
        cat_names = self.meta["cat_names"]
        n = len(np.asarray(data[(num_names + cat_names)[0]]))
        ll = np.log(np.maximum(priors, 1e-12))[None, :].repeat(n, 0)
        min_sd = max(self.meta["min_sdev"], 1e-6)
        eps = self.meta["eps_sdev"]
        for j, name in enumerate(num_names):
            x = np.asarray(data[name], dtype=np.float64)
            mu = self.arrays["num_mu"][j]
            sd = np.maximum(self.arrays["num_sd"][j], min_sd) + eps
            t = (x[:, None] - mu[None, :]) / sd[None, :]
            contrib = -0.5 * t * t - np.log(sd)[None, :]
            ll += np.where(np.isnan(x)[:, None], 0.0, contrib)
        min_p = max(self.meta["min_prob"], 1e-10)
        for j, name in enumerate(cat_names):
            dom = self.meta["cat_domains"][j]
            lut = {lvl: i for i, lvl in enumerate(dom)}
            v = np.asarray(data[name])
            codes = np.array([lut.get(str(x), -1) if x is not None else -1
                              for x in v], dtype=np.int64)
            probs = np.maximum(self.arrays[f"cat_table_{j}"], min_p)
            contrib = np.log(probs[:, np.maximum(codes, 0)]).T
            ll += np.where((codes < 0)[:, None], 0.0, contrib)
        p = np.exp(ll - ll.max(axis=1, keepdims=True))
        p = p / p.sum(axis=1, keepdims=True)
        # _class_output only consults the threshold in the 2-class case
        return _class_output(p, self.meta.get("default_threshold", 0.5),
                             self.domain)


class UpliftDrfMojoModel(SharedTreeMojoModel):
    def predict(self, data):
        B = int(self.meta["nbins_total"])
        bins = bin_raw(self.meta, self.arrays, data)
        pt = walk_forest({**self.arrays,
                          "tree_leaf": self.arrays["leaf_pt"]},
                         bins, B).mean(axis=0)
        pc = walk_forest({**self.arrays,
                          "tree_leaf": self.arrays["leaf_pc"]},
                         bins, B).mean(axis=0)
        return {"uplift_predict": pt - pc, "p_y1_ct1": pt, "p_y1_ct0": pc}


class ExtIsoForMojoModel(MojoModel):
    def predict(self, data):
        names = self.names
        means = self.arrays["col_means"]
        X = np.stack([np.asarray(data[n], dtype=np.float64)
                      for n in names], axis=1)
        for j in range(X.shape[1]):
            X[np.isnan(X[:, j]), j] = means[j]
        normals = self.arrays["ext_normals"]     # [T, D, L, F]
        offsets = self.arrays["ext_offsets"]
        is_split = self.arrays["ext_is_split"].astype(bool)
        leaf = self.arrays["ext_leaf"]
        T, D = normals.shape[0], normals.shape[1]
        n = X.shape[0]
        tot = np.zeros(n)
        for t in range(T):
            nid = np.zeros(n, dtype=np.int64)
            plen = np.zeros(n)
            for d in range(D):
                isp = is_split[t, d][nid]
                plen += isp
                Wr = normals[t, d][nid]
                proj = (X * Wr).sum(axis=1)
                goleft = np.where(isp, proj < offsets[t, d][nid], True)
                nid = 2 * nid + np.where(goleft, 0, 1)
            tot += plen + leaf[t][nid]
        ml = tot / T
        c = max(float(self.meta["c_norm"]), 1e-12)
        return {"anomaly_score": 2.0 ** (-ml / c), "mean_length": ml}


class GlrmMojoModel(MojoModel):
    def predict(self, data):
        """Project rows onto the archetypes: ridge solve of X ≈ A·Y with
        NA cells excluded per row (hex/genmodel/algos/glrm scoring role)."""
        X = design_matrix(self.meta, self.arrays, data)
        Y = self.arrays["archetypes"]            # [k, P]
        k = Y.shape[0]
        lam = 1e-6
        # NA mask in the expanded space: numeric NAs were mean-imputed by
        # design_matrix, so recover them from the raw columns
        n = X.shape[0]
        ok = np.ones_like(X, dtype=bool)
        col_idx = 0
        domains = self.meta.get("feature_domains") or [None] * len(self.names)
        for i, name in enumerate(self.names):
            dom = domains[i]
            # widths must mirror design_matrix exactly (card floor of 1)
            width = max(len(dom), 1) if dom is not None else 1
            v = np.asarray(data[name])
            if dom is None:
                isna = np.isnan(v.astype(np.float64))
            else:
                # same missing test design_matrix applies: None, NaN, or
                # a level outside the training domain all encode to -1
                domset = set(dom)
                isna = np.asarray([
                    x is None
                    or (isinstance(x, float) and np.isnan(x))
                    or str(x) not in domset
                    for x in v])
            ok[isna, col_idx: col_idx + width] = False
            col_idx += width
        A = np.zeros((n, k))
        G_full = Y @ Y.T + lam * np.eye(k)
        full = ok.all(axis=1)
        if full.any():
            A[full] = np.linalg.solve(G_full, Y @ X[full].T).T
        for r in np.where(~full)[0]:
            m = ok[r]
            Ym = Y[:, m]
            A[r] = np.linalg.solve(Ym @ Ym.T + lam * np.eye(k),
                                   Ym @ X[r, m])
        return {f"Arch{i + 1}": A[:, i] for i in range(k)}


class Word2VecMojoModel(MojoModel):
    def predict(self, data):
        """Embed a words column: NaN/None rows delimit sequences only in
        transform()-style use; here NONE semantics (one vector per word,
        NaN row for unknown)."""
        vocab = self.meta["vocab"]
        index = {w: i for i, w in enumerate(vocab)}
        vec = self.arrays["vectors"]
        words = np.asarray(data[self.names[0]] if self.names
                           else data["words"])
        D = vec.shape[1]
        out = np.full((len(words), D), np.nan)
        for i, w in enumerate(words):
            j = index.get(w if isinstance(w, str) else None)
            if j is not None:
                out[i] = vec[j]
        return {f"V{i + 1}": out[:, i] for i in range(D)}

    def find_synonyms(self, word: str, count: int = 20):
        vec = self.arrays["vectors"]
        index = {w: i for i, w in enumerate(self.meta["vocab"])}
        if word not in index:
            return {}
        v = vec[index[word]]
        sims = vec @ v / np.maximum(
            np.linalg.norm(vec, axis=1) * max(np.linalg.norm(v), 1e-12),
            1e-12)
        order = np.argsort(-sims)
        out = {}
        for i in order:
            w = self.meta["vocab"][i]
            if w == word:
                continue
            out[w] = float(sims[i])
            if len(out) >= count:
                break
        return out


class RuleFitMojoModel(MojoModel):
    """Composite offline scorer: rebuild the rule/linear feature columns
    from the bundled rule forests, then score the bundled GLM head."""

    def _features(self, data) -> Dict[str, np.ndarray]:
        from h2o3_tpu.genmodel.mojo import route_tree_nids
        feats: Dict[str, np.ndarray] = {}
        rules = self.meta["rules"]
        for i in range(int(self.meta["n_tree_models"])):
            sub_meta = {"names": self.meta[f"tm{i}_names"],
                        "nbins_total": self.meta[f"tm{i}_nbins_total"],
                        "feature_domains": self.meta[f"tm{i}_feature_domains"]}
            pre = f"tm{i}_"
            sub_arrays = {k[len(pre):]: v for k, v in self.arrays.items()
                          if k.startswith(pre)}
            B = int(sub_meta["nbins_total"])
            bins = bin_raw(sub_meta, sub_arrays, data)
            my_rules = [r for r in rules if r["model"] == i]
            by_tree: Dict[int, list] = {}
            for r in my_rules:
                by_tree.setdefault(int(r["tree"]), []).append(r)
            for t, rl in sorted(by_tree.items()):
                cs = sub_arrays.get("tree_cat_split")
                lw = sub_arrays.get("tree_left_words")
                nid = route_tree_nids(
                    sub_arrays["tree_feat"][t], sub_arrays["tree_thresh"][t],
                    sub_arrays["tree_na_left"][t].astype(bool),
                    sub_arrays["tree_is_split"][t].astype(bool), bins, B,
                    None if cs is None else cs[t].astype(bool),
                    None if lw is None else lw[t])
                for r in rl:
                    feats[r["name"]] = ((nid >= r["lo"]) & (nid < r["hi"])
                                        ).astype(np.float64)
        for n in self.meta.get("linear_cols") or []:
            lo, hi = self.meta["winsor"][n]
            v = np.asarray(data[n], dtype=np.float64)
            feats[f"linear.{n}"] = np.clip(v, lo, hi)
        return feats

    def predict(self, data):
        glm_arrays = {k[4:]: v for k, v in self.arrays.items()
                      if k.startswith("glm_")}
        head = GlmMojoModel(self.meta["glm"], glm_arrays)
        return head.predict(self._features(data))


_READERS = {
    "gbm": GbmMojoModel,
    "drf": DrfMojoModel,
    "isolationforest": IsoForMojoModel,
    "glm": GlmMojoModel,
    "deeplearning": DeepLearningMojoModel,
    "kmeans": KMeansMojoModel,
    "pca": PcaMojoModel,
    "svd": PcaMojoModel,
    "isotonicregression": IsotonicMojoModel,
    "coxph": CoxPHMojoModel,
    "naivebayes": NaiveBayesMojoModel,
    "upliftdrf": UpliftDrfMojoModel,
    "extendedisolationforest": ExtIsoForMojoModel,
    "word2vec": Word2VecMojoModel,
    "glrm": GlrmMojoModel,
    "rulefit": RuleFitMojoModel,
}
