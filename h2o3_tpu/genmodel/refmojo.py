"""Reference-format MOJO export for tree models (GBM/DRF).

Emits the ACTUAL reference MOJO zip layout — model.ini + domains/dNNN.txt
+ trees/tCC_TTT.bin — with tree blobs in the SharedTreeMojoModel v1.40
byte format, so the reference genmodel runtime can score our models:

- blob grammar (hex/tree/DTree.java compress() writer,
  hex/genmodel/algos/tree/SharedTreeMojoModel.java:129 scoreTree reader):
  node = [1B nodeType][2B colId][1B naSplitDir]
         [4B float splitVal | bitset]
         [left-subtree size (1-4B, width from nodeType bits 0-1,
          absent when left child is a leaf)]
         [left subtree][right subtree];  leaf = [4B float]
  nodeType bits: 0-1 left-size width-1, 2-3 equal (0 numeric,
  12 bitset via compress3), 48 left-is-leaf, 192 right-is-leaf.
- bitset (compress3, GenmodelBitSet.fill3): [2B bitoff=0][4B nbits]
  [ceil(nbits/8) bytes], bit set ⇔ category goes RIGHT (scoreTree:
  bs.contains(d) → right branch).
- numeric: go left ⇔ value < splitVal; our bin<=t split maps to
  splitVal = edges[f][t] exactly (bin counts edges <= x).
- byte order: native little-endian (ByteBufferWrapper nativeOrder).

`score_reference_mojo` is an independent decoder following the reader
byte-for-byte — the round-trip contract check this format ships with.
"""

from __future__ import annotations

import io
import struct
import uuid as _uuid
import zipfile
from typing import Dict, List, Optional

import numpy as np

NA_LEFT, NA_RIGHT = 2, 3                     # NaSplitDir NALeft / NARight


# ---------------------------------------------------------------- writer


def _leaf_bytes(val: float) -> bytes:
    return struct.pack("<f", float(val))


def _node_bytes(feat, thresh, na_left, is_split, cat_split, left_words,
                leaf, edges, cards, divs, d, l, D) -> bytes:
    """Serialize node (d, l) of a complete-layout tree, recursively."""
    if d == D or not is_split[d, l]:
        return _leaf_bytes(leaf[l << (D - d)])
    f = int(feat[d, l])
    t = int(thresh[d, l])
    nal = bool(na_left[d, l])
    left = _node_bytes(feat, thresh, na_left, is_split, cat_split,
                       left_words, leaf, edges, cards, divs,
                       d + 1, 2 * l, D)
    right = _node_bytes(feat, thresh, na_left, is_split, cat_split,
                        left_words, leaf, edges, cards, divs,
                        d + 1, 2 * l + 1, D)
    left_is_leaf = (d + 1 == D) or not is_split[d + 1, 2 * l]
    right_is_leaf = (d + 1 == D) or not is_split[d + 1, 2 * l + 1]

    node_type = 0
    payload = b""
    if bool(cat_split[d, l]):
        node_type |= 12                       # bitset split (compress3)
        card = int(cards[f])
        div = int(divs[f])
        words = left_words[d, l]
        bits = bytearray((card + 7) >> 3)
        for lvl in range(card):
            b = lvl // div
            in_left = (int(words[b >> 5]) >> (b & 31)) & 1
            if not in_left:                   # bitset marks RIGHT-goers
                bits[lvl >> 3] |= 1 << (lvl & 7)
        payload = struct.pack("<HI", 0, card) + bytes(bits)
    else:
        e = edges[f]
        sv = float(e[t]) if t < len(e) else float("inf")
        payload = struct.pack("<f", sv)

    if left_is_leaf:
        node_type |= 48
        size_field = b""
    else:
        lsz = len(left)
        if lsz < 256:
            slen, size_field = 0, struct.pack("<B", lsz)
        elif lsz < 65535:
            slen, size_field = 1, struct.pack("<H", lsz)
        elif lsz < (1 << 24):
            slen, size_field = 2, struct.pack("<I", lsz)[:3]
        else:
            slen, size_field = 3, struct.pack("<i", lsz)
        node_type |= slen
    if right_is_leaf:
        node_type |= 192
    head = struct.pack("<BHB", node_type, f, NA_LEFT if nal else NA_RIGHT)
    return head + payload + size_field + left + right


def _root_blob(feat, thresh, na_left, is_split, cat_split, left_words,
               leaf, edges, cards, divs, D) -> bytes:
    if not is_split[0, 0]:
        # root leaf: nodeType byte, colId 0xFFFF sentinel, float value
        return struct.pack("<BH", 0, 0xFFFF) + _leaf_bytes(leaf[0])
    return _node_bytes(feat, thresh, na_left, is_split, cat_split,
                       left_words, leaf, edges, cards, divs, 0, 0, D)


def write_reference_mojo(model, path: str) -> str:
    """Write a reference-layout MOJO zip for a GBM/DRF model."""
    from h2o3_tpu.models.model import ModelCategory
    bm = model.bm
    out = model.output
    f = model.forest
    feat = np.asarray(f.feat)
    thresh = np.asarray(f.thresh)
    na_left = np.asarray(f.na_left)
    is_split = np.asarray(f.is_split)
    cat_split = np.asarray(f.cat_split)
    left_words = np.asarray(f.left_words)
    leaf = np.asarray(f.leaf, np.float64)
    D = feat.shape[1]

    host_edges = np.asarray(bm.edges)
    edges = [e[np.isfinite(e)] for e in host_edges]
    cards = [len(d) if d else 1 for d in bm.domains]
    nb = np.asarray(bm.nbins)
    divs = [max(1, -(-cards[i] // max(int(nb[i]), 1)))
            if bm.is_cat[i] and cards[i] > int(nb[i]) else 1
            for i in range(len(cards))]

    cat = out["category"]
    K = out.get("nclasses", 1) if cat == ModelCategory.MULTINOMIAL else 1
    T_total = feat.shape[0]
    n_groups = T_total // K
    n_classes = (out.get("nclasses", 2)
                 if cat in (ModelCategory.BINOMIAL,
                            ModelCategory.MULTINOMIAL) else 1)

    names = list(bm.names) + [out["response"]]
    rdom = out.get("domain")
    domains: List[Optional[List[str]]] = list(bm.domains) + [rdom]

    info = _base_info(
        model,
        category={ModelCategory.BINOMIAL: "Binomial",
                  ModelCategory.MULTINOMIAL: "Multinomial"}.get(
                      cat, "Regression"),
        n_features=len(bm.names), n_classes=n_classes,
        n_columns=len(names),
        n_domains=sum(1 for d in domains if d is not None))
    info.update({
        "mojo_version": "1.40",
        "algo": model.algo,
        "algorithm": ("Gradient Boosting Machine" if model.algo == "gbm"
                      else "Distributed Random Forest"),
        "prior_class_distrib": "null",
        "model_class_distrib": "null",
        "timestamp": "2026-01-01 00:00:00",
        "n_trees": n_groups,
        "n_trees_per_class": K,
    })
    if model.algo == "gbm":
        link = {"bernoulli": "logit", "multinomial": "logit",
                "poisson": "log", "gamma": "log", "tweedie": "log"}.get(
                    model.dist_name, "identity")
        info.update(distribution=model.dist_name,
                    init_f=float(np.asarray(model.f0).ravel()[0]),
                    link_function=link)
    else:
        info.update(binomial_double_trees="false")

    def _blobs():
        for g in range(n_groups):
            for k in range(K):
                idx = g * K + k
                yield (f"trees/t{k:02d}_{g:03d}.bin", _root_blob(
                    feat[idx], thresh[idx], na_left[idx], is_split[idx],
                    cat_split[idx], left_words[idx], leaf[idx],
                    edges, cards, divs, D))
    return _emit_mojo_zip(path, info, names, domains, _blobs())


# ------------------------------------------------------ shared ini emission


def _base_info(model, category: str, n_features: int, n_classes: int,
               n_columns: int, n_domains: int) -> Dict[str, object]:
    """[info] fields every reference MOJO carries (ModelMojoReader
    readAll contract)."""
    return {
        "h2o_version": "3.46.0.1",
        "license": "Apache License Version 2.0",
        "endianness": "LITTLE_ENDIAN",
        "category": category,
        "uuid": str(abs(hash(model.key)) if model.key else
                    _uuid.uuid4().int % (1 << 63)),
        "supervised": "true",
        "n_features": n_features,
        "n_classes": n_classes,
        "n_columns": n_columns,
        "n_domains": n_domains,
        "balance_classes": "false",
        "default_threshold": model.output.get("default_threshold", 0.5),
    }


def _emit_mojo_zip(path: str, info: Dict[str, object], names: List[str],
                   domains: List[Optional[List[str]]],
                   blobs=None) -> str:
    """Write model.ini + domains/dNNN.txt (+ extra binary entries) —
    the zip layout both the tree and GLM writers share. ``blobs`` is an
    iterable of (entry_name, bytes) pairs, consumed lazily so a large
    forest never materializes every serialized tree at once."""
    ini = ["[info]"]
    ini += [f"{k} = {v}" for k, v in info.items()]
    ini += ["", "[columns]"]
    ini += names
    ini += ["", "[domains]"]
    dom_files: Dict[str, List[str]] = {}
    di = 0
    for i, d in enumerate(domains):
        if d is None:
            continue
        fn = f"d{di:03d}.txt"
        ini.append(f"{i}: {len(d)} {fn}")
        dom_files[fn] = list(d)
        di += 1
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("model.ini", "\n".join(ini) + "\n")
        for fn, lvls in dom_files.items():
            z.writestr(f"domains/{fn}", "\n".join(lvls) + "\n")
        for name, blob in (blobs or ()):
            z.writestr(name, blob)
    return path


# ----------------------------------------------------------- GLM writer


def _jarr(vals) -> str:
    """Java ``Arrays.toString`` serialization — the writekv array format
    (hex/genmodel/AbstractMojoWriter.java writekv(String, double[]))."""
    return "[" + ", ".join(repr(float(v)) if isinstance(v, float)
                           else str(int(v)) for v in vals) + "]"


def write_reference_glm_mojo(model, path: str) -> str:
    """Write a reference-layout GLM MOJO zip (GlmMojoReader v1.00
    contract, hex/glm/GLMMojoWriter.java writeModelData):

    - beta layout: categorical one-hot blocks first (catOffsets), then
      numerics, then intercept — RAW scale (GlmMojoModel.glmScore0
      applies no standardization).
    - columns reordered categoricals-first to match the data[] indexing
      ``i < cats ⇒ categorical code`` (GlmMojoModelBase).
    - NA semantics: cat_modes[i] = cardinality (an out-of-range code)
      reproduces our all-zero-indicator NA block exactly — glmScore0
      skips the coefficient when ival reaches catOffsets[i+1].
    """
    from h2o3_tpu.models.model import ModelCategory
    if model.coef_multinomial is not None or \
            model.output.get("family") == "ordinal":
        # ordinal trains with a placeholder intercept + separate
        # thresholds (ordinal_alphas) that GlmMojoModel cannot express
        raise ValueError("reference-format GLM MOJO export does not "
                         "cover multinomial/ordinal yet")
    feats = list(model.features)
    domains_by_feat = model.di_stats["domains"]
    use_all = bool(model.params.get("use_all_factor_levels", False))
    first = 0 if use_all else 1
    coefs = model.coefficients  # raw scale, keyed by coef name

    cats_i = [i for i, d in enumerate(domains_by_feat) if d is not None]
    nums_i = [i for i, d in enumerate(domains_by_feat) if d is None]
    cat_offsets = [0]
    beta: List[float] = []
    cat_modes: List[int] = []
    for i in cats_i:
        dom = domains_by_feat[i]
        for l in range(first, max(len(dom), 1)):
            beta.append(coefs[f"{feats[i]}.{dom[l]}"])
        cat_offsets.append(len(beta))
        cat_modes.append(max(len(dom), 1))
    num_means = [float(m) for m in model.di_stats["num_means"]]
    for i in nums_i:
        beta.append(coefs[feats[i]])
    beta.append(coefs["Intercept"])

    fam = model.family
    cat = model.output["category"]
    binomial = cat == ModelCategory.BINOMIAL
    names = ([feats[i] for i in cats_i] + [feats[i] for i in nums_i]
             + [model.output["response"]])
    domains: List[Optional[List[str]]] = (
        [list(domains_by_feat[i]) for i in cats_i]
        + [None] * len(nums_i) + [model.output.get("domain")])

    info = _base_info(model, category="Binomial" if binomial
                      else "Regression", n_features=len(feats),
                      n_classes=2 if binomial else 1,
                      n_columns=len(names),
                      n_domains=sum(1 for d in domains if d is not None))
    info.update({
        "mojo_version": "1.00",
        "algo": "glm",
        "algorithm": "Generalized Linear Model",
        # GLMMojoWriter.writeModelData kv block
        "use_all_factor_levels": "true" if use_all else "false",
        "cats": len(cats_i),
        "cat_offsets": _jarr(cat_offsets),
        "nums": len(nums_i),
        "mean_imputation": "true",
        "num_means": _jarr(num_means),
        "cat_modes": _jarr(cat_modes),
        "beta": _jarr([float(b) for b in beta]),
        "family": fam.name,
        "link": fam.link,
    })
    if fam.name == "tweedie":
        # our tweedie linkinv is exp (log link); power 0 selects
        # Math.exp in GenModel.GLM_tweedieInv
        info["tweedie_link_power"] = 0.0
    return _emit_mojo_zip(path, info, names, domains)


def _parse_jarr(s: str) -> List[float]:
    s = s.strip()[1:-1].strip()
    return [float(x) for x in s.split(",")] if s else []


def score_reference_glm_mojo(path: str, rows: Dict[str, np.ndarray]):
    """Faithful port of GlmMojoModel.score0 (mean imputation +
    glmScore0 + link inverse) reading our reference-layout GLM zip —
    the round-trip contract check. Returns mu [n]."""
    with zipfile.ZipFile(path) as z:
        ini = z.read("model.ini").decode().splitlines()
        info: Dict[str, str] = {}
        columns: List[str] = []
        domain_spec: Dict[int, str] = {}
        section = None
        for ln in ini:
            ln = ln.strip()
            if not ln:
                continue
            if ln in ("[info]", "[columns]", "[domains]"):
                section = ln
                continue
            if section == "[info]":
                k, _, v = ln.partition("=")
                info[k.strip()] = v.strip()
            elif section == "[columns]":
                columns.append(ln)
            elif section == "[domains]":
                ci, _, rest = ln.partition(":")
                domain_spec[int(ci)] = rest.strip().split(" ", 1)[1]
        domains = {ci: z.read(f"domains/{fn}").decode().splitlines()
                   for ci, fn in domain_spec.items()}

    cats = int(info["cats"])
    nums = int(info["nums"])
    cat_offsets = [int(v) for v in _parse_jarr(info["cat_offsets"])]
    cat_modes = [int(v) for v in _parse_jarr(info["cat_modes"])]
    num_means = _parse_jarr(info["num_means"])
    beta = _parse_jarr(info["beta"])
    use_all = info["use_all_factor_levels"] == "true"
    link = info["link"]
    tlp = float(info.get("tweedie_link_power", 0.0))

    n = len(next(iter(rows.values())))
    data = np.full((n, cats + nums), np.nan)
    for i in range(cats + nums):
        cn = columns[i]
        v = rows[cn]
        if i < cats:
            lut = {s: j for j, s in enumerate(domains[i])}
            data[:, i] = [lut.get(str(x), np.nan)
                          if x is not None else np.nan for x in v]
        else:
            data[:, i] = np.asarray(v, np.float64)

    mu = np.empty(n)
    for r in range(n):
        row = data[r].copy()
        for i in range(cats):                 # imputeMissingWithMeans
            if np.isnan(row[i]):
                row[i] = cat_modes[i]
        for i in range(nums):
            if np.isnan(row[cats + i]):
                row[cats + i] = num_means[i]
        eta = 0.0
        for i in range(cats):                 # glmScore0 cat walk
            ival = int(row[i]) - (0 if use_all else 1)
            if not use_all and row[i] == 0:
                continue
            ival += cat_offsets[i]
            if ival < cat_offsets[i + 1]:
                eta += beta[ival]
        noff = cat_offsets[cats] - cats
        for i in range(cats, len(beta) - 1 - noff):
            eta += beta[noff + i] * row[i]
        eta += beta[-1]
        if link == "identity":
            m = eta
        elif link == "logit":
            m = 1.0 / (1.0 + np.exp(-eta))
        elif link == "log":
            m = np.exp(eta)
        elif link == "inverse":
            xx = min(-1e-5, eta) if eta < 0 else max(1e-5, eta)
            m = 1.0 / xx
        elif link == "tweedie":
            m = max(2e-16, np.exp(eta)) if tlp == 0 \
                else float(np.power(eta, 1.0 / tlp))
        else:
            raise ValueError(link)
        mu[r] = m
    return mu, info


# ------------------------------------------------- reference-contract reader


def _score_tree(blob: bytes, row: np.ndarray, domains_len) -> float:
    """Byte-faithful port of SharedTreeMojoModel.scoreTree (v1.40)."""
    pos = 0

    def get1():
        nonlocal pos
        v = blob[pos]
        pos += 1
        return v

    def get2():
        nonlocal pos
        v = struct.unpack_from("<H", blob, pos)[0]
        pos += 2
        return v

    def get4f():
        nonlocal pos
        v = struct.unpack_from("<f", blob, pos)[0]
        pos += 4
        return v

    def getsize(w):
        nonlocal pos
        if w == 0:
            return get1()
        if w == 1:
            return get2()
        if w == 2:
            v = blob[pos] | (blob[pos + 1] << 8) | (blob[pos + 2] << 16)
            pos += 3
            return v
        v = struct.unpack_from("<i", blob, pos)[0]
        pos += 4
        return v

    while True:
        node_type = get1()
        col_id = get2()
        if col_id == 65535:
            return get4f()
        na_split_dir = get1()
        na_vs_rest = na_split_dir == 1
        leftward = na_split_dir in (2, 4)
        lmask = node_type & 51
        equal = node_type & 12

        split_val = None
        bs = None
        if not na_vs_rest:
            if equal == 0:
                split_val = get4f()
            else:
                if equal == 8:
                    bitoff, nbits, bs_off = 0, 32, pos
                    pos += 4
                else:
                    bitoff = get2()
                    nbits = struct.unpack_from("<i", blob, pos)[0]
                    pos += 4
                    bs_off = pos
                    pos += ((nbits - 1) >> 3) + 1
                bs = (bitoff, nbits, bs_off)

        d = row[col_id]
        out_of_bs = False
        if equal != 0 and bs is not None and not np.isnan(d):
            b = int(d) - bs[0]
            out_of_bs = not (0 <= b < bs[1])
        dlen = domains_len[col_id]
        out_of_dom = (dlen is not None and not np.isnan(d)
                      and dlen <= int(d))
        if np.isnan(d) or out_of_bs or out_of_dom:
            go_right = not leftward
        elif na_vs_rest:
            go_right = False
        elif equal == 0:
            go_right = d >= split_val
        else:
            idx = int(d) - bs[0]
            go_right = bool(blob[bs[2] + (idx >> 3)] & (1 << (idx & 7)))

        if go_right:
            if lmask <= 3:
                skip = getsize(lmask)
                pos += skip
            elif lmask == 48:
                pos += 4                     # skip the left-leaf float
            lmask = (node_type & 0xC0) >> 2
        else:
            if lmask <= 3:
                pos += lmask + 1             # skip the size field
        if lmask & 16:
            return get4f()


def score_reference_mojo(path: str, rows: Dict[str, np.ndarray]):
    """Score raw rows with a reference-layout MOJO using the ported
    reader — validates our zips honor the reference contract. Returns
    the raw per-group margins [n, n_groups_or_K] (no link applied)."""
    with zipfile.ZipFile(path) as z:
        ini = z.read("model.ini").decode().splitlines()
        info: Dict[str, str] = {}
        columns: List[str] = []
        domain_spec: Dict[int, str] = {}
        section = None
        for ln in ini:
            ln = ln.strip()
            if not ln or ln.startswith("#"):
                continue
            if ln in ("[info]", "[columns]", "[domains]"):
                section = ln
                continue
            if section == "[info]":
                k, _, v = ln.partition("=")
                info[k.strip()] = v.strip()
            elif section == "[columns]":
                columns.append(ln)
            elif section == "[domains]":
                ci, _, rest = ln.partition(":")
                domain_spec[int(ci)] = rest.strip().split(" ", 1)[1]
        n_features = int(info["n_features"])
        n_groups = int(info["n_trees"])
        tpc = int(info["n_trees_per_class"])
        domains = {}
        for ci, fn in domain_spec.items():
            domains[ci] = z.read(f"domains/{fn}").decode().splitlines()
        # rows → double[] in column order (categoricals as domain index)
        n = len(next(iter(rows.values())))
        mat = np.full((n, n_features), np.nan)
        domains_len = [None] * n_features
        for i in range(n_features):
            cn = columns[i]
            v = rows[cn]
            if i in domains:
                lut = {s: j for j, s in enumerate(domains[i])}
                mat[:, i] = [lut.get(str(x), np.nan)
                             if x is not None else np.nan for x in v]
                domains_len[i] = len(domains[i])
            else:
                mat[:, i] = np.asarray(v, np.float64)
        out = np.zeros((n, tpc))
        for k in range(tpc):
            for g in range(n_groups):
                blob = z.read(f"trees/t{k:02d}_{g:03d}.bin")
                for r in range(n):
                    out[r, k] += _score_tree(blob, mat[r], domains_len)
        return out, info


# ------------------------------------------------- KMeans writer/reader


def write_reference_kmeans_mojo(model, path: str) -> str:
    """Reference-layout K-means MOJO (KMeansMojoReader v1.00 contract):
    model.ini kv pairs ``standardize``/``standardize_means``/
    ``standardize_mults``/``standardize_modes``/``center_num``/
    ``center_i``. Centers are written in STANDARDIZED space when
    standardize=true — KMeansMojoModel.score0 preprocesses the row with
    (x - mean) * mult before KMeans_closest. Numeric feature sets only:
    the reference handles categoricals through domain indices while our
    KMeans one-hot expands them (different geometry)."""
    if any(d is not None for d in model.di_stats["domains"]):
        raise ValueError("reference-format KMeans MOJO export covers "
                         "numeric feature sets (our KMeans one-hot "
                         "expands categoricals; the reference does not)")
    feats = list(model.features)
    centers = np.asarray(model.centers_std, np.float64)
    means = [float(m) for m in model.di_stats["num_means"]]
    sds = [float(s) if s > 0 else 1.0
           for s in model.di_stats["num_sigmas"]]
    info = _base_info(model, category="Clustering",
                      n_features=len(feats), n_classes=1,
                      n_columns=len(feats), n_domains=0)
    info.update({
        "mojo_version": "1.00",
        "algo": "kmeans",
        "algorithm": "K-means",
        "supervised": "false",
        "standardize": "true" if model.standardize else "false",
        "center_num": centers.shape[0],
    })
    if model.standardize:
        info["standardize_means"] = _jarr(means)
        info["standardize_mults"] = _jarr([1.0 / s for s in sds])
        info["standardize_modes"] = _jarr([0] * len(feats))
    for i in range(centers.shape[0]):
        info[f"center_{i}"] = _jarr([float(v) for v in centers[i]])
    return _emit_mojo_zip(path, info, feats, [None] * len(feats))


def score_reference_kmeans_mojo(path: str, rows: Dict[str, np.ndarray]):
    """Cluster assignment from a reference KMeans MOJO — the ported
    KMeansMojoModel.score0 (preprocess + KMeans_closest)."""
    info, columns, _ = _read_ini(path)
    n_feat = int(info["n_features"])
    k = int(info["center_num"])
    centers = np.stack([_parse_jarr(info[f"center_{i}"])
                        for i in range(k)])
    n = len(next(iter(rows.values())))
    mat = np.zeros((n, n_feat))
    for i in range(n_feat):
        mat[:, i] = np.asarray(rows[columns[i]], np.float64)
    if info.get("standardize") == "true":
        means = np.asarray(_parse_jarr(info["standardize_means"]))
        mults = np.asarray(_parse_jarr(info["standardize_mults"]))
        mat = (mat - means) * mults
    d2 = ((mat[:, None, :] - centers[None, :, :]) ** 2).sum(axis=2)
    return d2.argmin(axis=1), info


# ------------------------------------------- DeepLearning writer/reader


def _dl_ref_layout(model):
    """Our feature-order design vs the reference's cats-first layout.

    Returns (cats_i, nums_i, ref_to_ours): reference input unit j maps
    to our design-matrix column ref_to_ours[j]
    (DeeplearningMojoModel.score0 fills neuronsInput as [one-hot cat
    blocks..., standardized nums...]; our DataInfo expands in feature
    order)."""
    domains = model.di_stats["domains"]
    use_all = bool(model.params.get("use_all_factor_levels", True))
    first = 0 if use_all else 1
    ours = []           # per feature: list of our design column indices
    pos = 0
    for d in domains:
        if d is not None:
            kk = max(len(d), 1) - first
            ours.append(list(range(pos, pos + kk)))
            pos += kk
        else:
            ours.append([pos])
            pos += 1
    cats_i = [i for i, d in enumerate(domains) if d is not None]
    nums_i = [i for i, d in enumerate(domains) if d is None]
    ref_to_ours = []
    for i in cats_i:
        ref_to_ours += ours[i]
    for i in nums_i:
        ref_to_ours += ours[i]
    return cats_i, nums_i, ref_to_ours


def write_reference_dl_mojo(model, path: str) -> str:
    """Reference-layout DeepLearning MOJO (DeeplearningMojoReader v1.10
    contract): model.ini kv with per-layer ``weight_layerN`` (row-major
    [out, in] doubles) / ``bias_layerN``, normalization stats, and the
    cats-first input layout — first-layer weight columns are permuted
    from our feature-order design accordingly. NA categorical rows
    diverge (we encode NA as the all-zero indicator block; the reference
    imputes the mode level)."""
    from h2o3_tpu.models.model import ModelCategory
    cat = model.output["category"]
    feats = list(model.features)
    domains = model.di_stats["domains"]
    cats_i, nums_i, ref_to_ours = _dl_ref_layout(model)
    use_all = bool(model.params.get("use_all_factor_levels", True))
    first = 0 if use_all else 1

    means = [float(m) for m in model.di_stats["num_means"]]
    sds = [float(s) if s > 0 else 1.0
           for s in model.di_stats["num_sigmas"]]
    cat_offsets = [0]
    for i in cats_i:
        cat_offsets.append(cat_offsets[-1]
                           + max(len(domains[i]), 1) - first)

    layers = [(np.asarray(p["W"], np.float64), np.asarray(p["b"], np.float64))
              for p in model.net]
    units = [layers[0][0].shape[0]] + [b.shape[0] for _, b in layers]

    n_classes = (model.output.get("nclasses", 1)
                 if cat in (ModelCategory.BINOMIAL,
                            ModelCategory.MULTINOMIAL) else 1)
    names = ([feats[i] for i in cats_i] + [feats[i] for i in nums_i]
             + [model.output["response"]])
    doms: List[Optional[List[str]]] = (
        [list(domains[i]) for i in cats_i] + [None] * len(nums_i)
        + [model.output.get("domain")])
    info = _base_info(model, category={
        ModelCategory.BINOMIAL: "Binomial",
        ModelCategory.MULTINOMIAL: "Multinomial"}.get(cat, "Regression"),
        n_features=len(feats), n_classes=max(n_classes, 1),
        n_columns=len(names),
        n_domains=sum(1 for d in doms if d is not None))
    dist = "bernoulli" if cat == ModelCategory.BINOMIAL else \
        ("multinomial" if cat == ModelCategory.MULTINOMIAL else "gaussian")
    info.update({
        "mojo_version": "1.10",
        "algo": "deeplearning",
        "algorithm": "Deep Learning",
        "mini_batch_size": 1,
        "nums": len(nums_i),
        "cats": len(cats_i),
        "cat_offsets": _jarr(cat_offsets),
        "norm_mul": _jarr([1.0 / s for s in sds]),
        "norm_sub": _jarr(means),
        "use_all_factor_levels": "true" if use_all else "false",
        "activation": str(model.params.get("activation", "Rectifier")),
        "mean_imputation": "false",
        "distribution": dist,
        "neural_network_sizes": _jarr(units),
        "hidden_dropout_ratios": _jarr([]),
        "_genmodel_encoding": "AUTO",
    })
    if cat == ModelCategory.REGRESSION and model.resp_stats is not None:
        mu, sd = model.resp_stats
        info["norm_resp_mul"] = _jarr([1.0 / (sd if sd else 1.0)])
        info["norm_resp_sub"] = _jarr([float(mu)])
    for li, (W, b) in enumerate(layers):
        # ours: z = x @ W ([in, out]); reference: w[out_row * in + col]
        Wr = W.T.copy()                        # [out, in]
        if li == 0:
            Wr = Wr[:, ref_to_ours]            # permute to cats-first
        info[f"weight_layer{li}"] = _jarr([float(v)
                                           for v in Wr.ravel()])
        info[f"bias_layer{li}"] = _jarr([float(v) for v in b])
    return _emit_mojo_zip(path, info, names, doms)


def _read_ini(path: str):
    with zipfile.ZipFile(path) as z:
        ini = z.read("model.ini").decode().splitlines()
        info: Dict[str, str] = {}
        columns: List[str] = []
        domain_spec: Dict[int, List[str]] = {}
        section = None
        for ln in ini:
            ln = ln.strip()
            if not ln:
                continue
            if ln in ("[info]", "[columns]", "[domains]"):
                section = ln
                continue
            if section == "[info]":
                k, _, v = ln.partition("=")
                info[k.strip()] = v.strip()
            elif section == "[columns]":
                columns.append(ln)
            elif section == "[domains]":
                ci, _, rest = ln.partition(":")
                fn = rest.strip().split(" ", 1)[1]
                domain_spec[int(ci)] = \
                    z.read(f"domains/{fn}").decode().splitlines()
    return info, columns, domain_spec


def score_reference_dl_mojo(path: str, rows: Dict[str, np.ndarray]):
    """Forward pass from a reference DL MOJO — the ported
    DeeplearningMojoModel.score0/NeuralNetwork semantics (cats-first
    input assembly, (x-sub)*mul normalization, row-major weights,
    hidden activation + linear output). Returns the raw output layer
    [n, out] plus the info dict (caller applies softmax/response
    denorm per category, as the reference's caller does)."""
    info, columns, domain_spec = _read_ini(path)
    n_cats = int(info["cats"])
    n_nums = int(info["nums"])
    cat_offsets = [int(v) for v in _parse_jarr(info["cat_offsets"])]
    norm_mul = np.asarray(_parse_jarr(info["norm_mul"]))
    norm_sub = np.asarray(_parse_jarr(info["norm_sub"]))
    use_all = info.get("use_all_factor_levels") == "true"
    units = [int(v) for v in _parse_jarr(info["neural_network_sizes"])]
    act = info.get("activation", "Rectifier").lower()

    n = len(next(iter(rows.values())))
    X = np.zeros((n, units[0]))
    first = 0 if use_all else 1
    for ci in range(n_cats):
        dom = domain_spec[ci]
        lut = {s: j for j, s in enumerate(dom)}
        codes = np.asarray([lut.get(str(v), -1)
                            for v in rows[columns[ci]]])
        base = cat_offsets[ci]
        for r in range(n):
            c = codes[r]
            if c >= first:
                X[r, base + c - first] = 1.0
    for ni in range(n_nums):
        v = np.asarray(rows[columns[n_cats + ni]], np.float64)
        X[:, cat_offsets[n_cats] + ni] = (v - norm_sub[ni]) * norm_mul[ni]

    h = X
    for li in range(len(units) - 1):
        W = np.asarray(_parse_jarr(info[f"weight_layer{li}"]))
        b = np.asarray(_parse_jarr(info[f"bias_layer{li}"]))
        W = W.reshape(units[li + 1], units[li])
        h = h @ W.T + b
        if li < len(units) - 2:
            if "tanh" in act:
                h = np.tanh(h)
            else:
                h = np.maximum(h, 0.0)
    return h, info


# ----------------------------------------- IsolationForest writer/reader


def _leaf_split_counts(is_split: np.ndarray) -> np.ndarray:
    """Per-leaf count of split nodes along its root path. Depth-major
    trees: leaf l at full depth D passes node l >> (D - d) at depth d."""
    D = is_split.shape[0]
    counts = np.zeros(2 ** D, np.float64)
    for l in range(2 ** D):
        for d in range(D):
            counts[l] += float(is_split[d][l >> (D - d)])
    return counts


def write_reference_isofor_mojo(model, path: str) -> str:
    """Reference-layout IsolationForest MOJO
    (IsolationForestMojoReader v1.40: SharedTree blobs + the
    min/max_path_length kv pair,
    hex/tree/isofor/IsolationForestMojoWriter.java:31).

    IsolationForestMojoModel.unifyPreds sums LEAF values over trees, so
    each exported leaf bakes in its full path length: the count of
    split nodes on the root path plus our stored c(n) tail correction —
    the walk then reproduces _tree_path_length exactly."""
    bm = model.bm
    f = model.forest
    feat = np.asarray(f.feat)
    thresh = np.asarray(f.thresh)
    na_left = np.asarray(f.na_left)
    is_split = np.asarray(f.is_split)
    cat_split = np.asarray(f.cat_split)
    left_words = np.asarray(f.left_words)
    leaf = np.asarray(f.leaf, np.float64)
    T, D, _ = feat.shape

    host_edges = np.asarray(bm.edges)
    edges = [e[np.isfinite(e)] for e in host_edges]
    cards = [len(d) if d else 1 for d in bm.domains]
    nb = np.asarray(bm.nbins)
    divs = [max(1, -(-cards[i] // max(int(nb[i]), 1)))
            if bm.is_cat[i] and cards[i] > int(nb[i]) else 1
            for i in range(len(cards))]

    names = list(bm.names)
    domains: List[Optional[List[str]]] = list(bm.domains)
    info = _base_info(model, category="AnomalyDetection",
                      n_features=len(names), n_classes=1,
                      n_columns=len(names),
                      n_domains=sum(1 for d in domains if d is not None))
    info.update({
        "mojo_version": "1.40",
        "algo": "isolationforest",
        "algorithm": "Isolation Forest",
        "supervised": "false",
        "n_trees": T,
        "n_trees_per_class": 1,
        "min_path_length": int(model.output.get("min_path_length", 0)),
        "max_path_length": int(model.output.get("max_path_length", 0)),
        "output_anomaly_flag": "false",
    })

    def _blobs():
        for t in range(T):
            full_leaf = leaf[t] + _leaf_split_counts(is_split[t])
            yield (f"trees/t00_{t:03d}.bin", _root_blob(
                feat[t], thresh[t], na_left[t], is_split[t],
                cat_split[t], left_words[t], full_leaf,
                edges, cards, divs, D))
    return _emit_mojo_zip(path, info, names, domains, _blobs())


def score_reference_isofor_mojo(path: str, rows: Dict[str, np.ndarray]):
    """Anomaly score + mean path length from a reference isofor MOJO —
    the ported IsolationForestMojoModel.unifyPreds."""
    tot, info = score_reference_mojo(path, rows)
    tot = tot[:, 0]
    T = int(info["n_trees"])
    mn = float(info["min_path_length"])
    mx = float(info["max_path_length"])
    score = ((mx - tot) / (mx - mn)) if mx > mn else np.ones_like(tot)
    return {"predict": score, "mean_length": tot / T}, info


# --------------------------------------------- Word2Vec writer/reader


def write_reference_word2vec_mojo(model, path: str) -> str:
    """Reference-layout Word2Vec MOJO (Word2VecMojoReader v1.00
    contract): kv vocab_size/vec_size, binary big-endian float32
    ``vectors`` blob, and a ``vocabulary`` text entry — read back via
    ByteBuffer.getFloat (big-endian) in reader order."""
    vecs = np.asarray(model.vectors, np.float32)
    vocab = list(model.vocab)
    V, Dv = vecs.shape
    info = _base_info(model, category="WordEmbedding",
                      n_features=1, n_classes=1, n_columns=1,
                      n_domains=0)
    info.update({
        "mojo_version": "1.00",
        "algo": "word2vec",
        "algorithm": "Word2Vec",
        "supervised": "false",
        "vocab_size": V,
        "vec_size": Dv,
    })
    blobs = [("vectors", vecs.astype(">f4").tobytes()),
             ("vocabulary", ("\n".join(vocab) + "\n").encode())]
    return _emit_mojo_zip(path, info, ["Word"], [None], blobs)


def read_reference_word2vec_mojo(path: str):
    """Independent decode: {word: float32[vec_size]} exactly as
    Word2VecMojoReader.readModelData builds its embeddings map."""
    info, _, _ = _read_ini(path)
    V = int(info["vocab_size"])
    Dv = int(info["vec_size"])
    with zipfile.ZipFile(path) as z:
        raw = z.read("vectors")
        vocab = z.read("vocabulary").decode().splitlines()
    if len(raw) != V * Dv * 4:
        raise IOError(f"corrupted vectors blob: {len(raw)} bytes")
    mat = np.frombuffer(raw, dtype=">f4").reshape(V, Dv)
    if len(vocab) != V:
        raise IOError(f"corrupted vocabulary: {len(vocab)} words")
    return {w: mat[i] for i, w in enumerate(vocab)}, info


def _cats_first_perm(domains_by_feat, keep_all_levels: bool):
    """Cats-first reorder shared by the CoxPH/GLRM writers: per-feature
    design-column blocks (in frame order), the categorical/numeric
    feature indices, and the design-column permutation that moves
    categorical blocks first (the MojoModel data[] layout)."""
    blocks, j = [], 0
    for d in domains_by_feat:
        if d is not None:
            w = max(len(d), 1) - (0 if keep_all_levels else 1)
        else:
            w = 1
        blocks.append(list(range(j, j + w)))
        j += w
    cats_i = [i for i, d in enumerate(domains_by_feat) if d is not None]
    nums_i = [i for i, d in enumerate(domains_by_feat) if d is None]
    perm = [c for i in cats_i for c in blocks[i]] + \
        [c for i in nums_i for c in blocks[i]]
    return blocks, cats_i, nums_i, perm, j


# ------------------------------------------------- CoxPH writer/reader


def write_reference_coxph_mojo(model, path: str) -> str:
    """Reference-layout CoxPH MOJO (CoxPHMojoReader v1.00 contract):
    coef over [cat one-hot blocks..., nums...] with cat_offsets,
    big-endian x_mean_cat/x_mean_num rectangular blobs per stratum, and
    lpBase derived BY THE READER as coef . x_mean
    (CoxPHMojoModel.computeLpBase) — so score0 returns
    lp - coef . x_mean, our centered linear predictor.

    Our design expands features in frame order; the reference wants
    categoricals first. Coefficients and the training design-column
    means (output["x_mean_design"], recorded at fit) are permuted
    accordingly. Strata/interactions are not exported (raises)."""
    if model.params.get("stratify_by"):
        raise ValueError("reference-format CoxPH MOJO export does not "
                         "cover stratified models yet")
    feats = list(model.features)
    domains_by_feat = model.di_stats["domains"]
    coef = np.asarray(model.coef, np.float64)
    xmean = np.asarray(model.output["x_mean_design"], np.float64)
    if len(xmean) != len(coef):
        raise ValueError("x_mean_design missing/stale — retrain to export")

    # our design column index blocks per feature, in feature order
    # (use_all_factor_levels=False drops the base level per block)
    blocks, cats_i, nums_i, perm, _ = _cats_first_perm(
        domains_by_feat, keep_all_levels=False)
    coef_ref = coef[perm]
    xmean_ref = xmean[perm]

    cat_offsets = [0]
    for i in cats_i:
        cat_offsets.append(cat_offsets[-1] + len(blocks[i]))
    n_cat_coef = cat_offsets[-1]

    names = [feats[i] for i in cats_i] + [feats[i] for i in nums_i]
    domains: List[Optional[List[str]]] = \
        [list(domains_by_feat[i]) for i in cats_i] + [None] * len(nums_i)
    info = _base_info(model, category="CoxPH", n_features=len(names),
                      n_classes=1, n_columns=len(names),
                      n_domains=len(cats_i))
    info.update({
        "mojo_version": "1.00",
        "algo": "coxph",
        "algorithm": "CoxPH",
        "coef": _jarr([float(v) for v in coef_ref]),
        "cats": len(cats_i),
        "cat_offsets": _jarr(cat_offsets),
        "use_all_factor_levels": "false",
        "strata_count": 0,
        "x_mean_cat_size1": 1,
        "x_mean_cat_size2": n_cat_coef,
        "x_mean_num_size1": 1,
        "x_mean_num_size2": len(coef_ref) - n_cat_coef,
        "interactions_1": "null",
        "interactions_2": "null",
        "interaction_targets": "null",
    })
    blobs = [("x_mean_cat", xmean_ref[:n_cat_coef].astype(">f8").tobytes()),
             ("x_mean_num", xmean_ref[n_cat_coef:].astype(">f8").tobytes())]
    return _emit_mojo_zip(path, info, names, domains, blobs)


def score_reference_coxph_mojo(path: str, rows: Dict[str, np.ndarray]):
    """lp from a reference CoxPH MOJO — the ported
    CoxPHMojoModel.score0 (cats-first data[], one-hot coef lookup,
    lpBase = coef . x_mean subtracted)."""
    info, columns, domain_spec = _read_ini(path)
    coef = np.asarray(_parse_jarr(info["coef"]))
    cat_offsets = [int(v) for v in _parse_jarr(info["cat_offsets"])]
    n_cats = int(info["cats"])
    with zipfile.ZipFile(path) as z:
        xm_cat = np.frombuffer(z.read("x_mean_cat"), dtype=">f8")
        xm_num = np.frombuffer(z.read("x_mean_num"), dtype=">f8")
    lp_base = float(coef[:len(xm_cat)] @ xm_cat
                    + coef[len(xm_cat):] @ xm_num)
    n = len(next(iter(rows.values())))
    lp = np.zeros(n)
    # categoricals: data[] carries the domain code; skip first level
    for ci in range(n_cats):
        dom = domain_spec[ci]
        lut = {s: j for j, s in enumerate(dom)}
        codes = np.asarray([lut.get(str(v), -1) for v in rows[columns[ci]]])
        for r in range(n):
            val = codes[r] - 1            # use_all_factor_levels=false
            x = val + cat_offsets[ci]
            if 0 <= val and x < cat_offsets[ci + 1]:
                lp[r] += coef[x]
    # numerics follow the categorical coefficient block
    for ni, cn in enumerate(columns[n_cats:]):
        v = np.asarray(rows[cn], np.float64)
        lp += coef[cat_offsets[-1] + ni] * v
    return lp - lp_base, info


# -------------------------------------------------- GLRM writer/reader


def write_reference_glrm_mojo(model, path: str) -> str:
    """Reference-layout GLRM MOJO (GlrmMojoReader v1.00+ contract):
    kv dims (ncolA/ncolY/nrowY/ncolX), regularizationX/gammaX/
    initialization, norm_sub/norm_mul, cols_permutation (cats first),
    num_levels_per_category, a ``losses`` text entry, and the
    big-endian double ``archetypes`` blob [nrowY, ncolY] read via
    ByteBuffer.getDouble; transposed=false so archetypes_raw is the
    matrix as written."""
    feats = list(model.features)
    domains_by_feat = model.di_stats["domains"]
    Y = np.asarray(model.Y, np.float64)              # [k, P_design]
    k = Y.shape[0]

    blocks, cats_i, nums_i, perm, width = _cats_first_perm(
        domains_by_feat, keep_all_levels=True)   # GLRM keeps all levels
    if width != Y.shape[1]:
        raise ValueError(
            f"GLRM archetype width {Y.shape[1]} != design width {width} "
            "(use_all_factor_levels mismatch)")
    Yref = Y[:, perm]

    num_means = [float(m) for m in model.di_stats["num_means"]]
    num_sigmas = [float(s) if s > 0 else 1.0
                  for s in model.di_stats["num_sigmas"]]
    stdize = model.transform == "standardize"
    norm_sub = num_means if stdize else [0.0] * len(nums_i)
    norm_mul = [1.0 / s for s in num_sigmas] if stdize \
        else [1.0] * len(nums_i)

    losses = ["Categorical"] * len(cats_i) + ["Quadratic"] * len(nums_i)
    names = [feats[i] for i in cats_i] + [feats[i] for i in nums_i]
    domains: List[Optional[List[str]]] = \
        [list(domains_by_feat[i]) for i in cats_i] + [None] * len(nums_i)
    info = _base_info(model, category="DimReduction",
                      n_features=len(names), n_classes=1,
                      n_columns=len(names), n_domains=len(cats_i))
    info.update({
        "mojo_version": "1.10",
        "algo": "glrm",
        "algorithm": "Generalized Low Rank Modeling",
        "supervised": "false",
        "ncolA": len(feats),
        "ncolY": Yref.shape[1],
        "nrowY": k,
        "ncolX": k,
        "regularizationX": str(model.params.get("regularization_x",
                                                "None")),
        "gammaX": float(model.params.get("gamma_x", 0.0)),
        "initialization": "PlusPlus",
        "num_categories": len(cats_i),
        "num_numeric": len(nums_i),
        "norm_sub": _jarr(norm_sub),
        "norm_mul": _jarr(norm_mul),
        "cols_permutation": _jarr(cats_i + nums_i),
        "num_levels_per_category": _jarr(
            [max(len(domains_by_feat[i]), 1) for i in cats_i]),
        "seed": int(model.params.get("seed", 0) or 0),
        "reverse_transform": "true" if stdize else "false",
        "transposed": "false",
        "catOffsets": _jarr(np.concatenate(
            [[0], np.cumsum([max(len(domains_by_feat[i]), 1)
                             for i in cats_i])]).astype(int)
            if cats_i else [0]),
    })
    blobs = [("archetypes", Yref.astype(">f8").tobytes()),
             ("losses", ("\n".join(losses) + "\n").encode())]
    return _emit_mojo_zip(path, info, names, domains, blobs)


def read_reference_glrm_mojo(path: str):
    """Independent decode of archetypes/norms/losses exactly as
    GlrmMojoReader.readModelData walks them."""
    info, columns, domain_spec = _read_ini(path)
    nrowY = int(info["nrowY"])
    ncolY = int(info["ncolY"])
    with zipfile.ZipFile(path) as z:
        arch = np.frombuffer(z.read("archetypes"),
                             dtype=">f8").reshape(nrowY, ncolY)
        losses = z.read("losses").decode().splitlines()
    return {"archetypes": arch,
            "losses": losses,
            "norm_sub": np.asarray(_parse_jarr(info["norm_sub"])),
            "norm_mul": np.asarray(_parse_jarr(info["norm_mul"])),
            "permutation": [int(v) for v in
                            _parse_jarr(info["cols_permutation"])],
            "num_levels": [int(v) for v in _parse_jarr(
                info["num_levels_per_category"])]}, info


# ------------------------------------------------- PCA writer/reader


def write_reference_pca_mojo(model, path: str) -> str:
    """Reference-layout PCA MOJO (PCAMojoReader v1.00 contract —
    hex/genmodel/algos/pca/PCAMojoReader.java:17): kv entries for
    ncats/nnums/catOffsets/permutation/normSub/normMul plus ONE
    big-endian double blob ``eigenvectors_raw`` of shape
    [eigenvector_size][k] in cats-first design order
    (PCAMojoModel.score0 walks cat one-hot blocks first, then
    (x-normSub)*normMul nums).

    Our design expands features in frame order; rows of the eigenvector
    matrix are permuted cats-first to match."""
    feats = list(model.features)
    domains_by_feat = model.di_stats["domains"]
    keep_all = bool(model.use_all_levels)
    blocks, cats_i, nums_i, perm, P = _cats_first_perm(
        domains_by_feat, keep_all_levels=keep_all)
    V = np.asarray(model.eigvecs, np.float64)          # [P, k], our order
    if V.shape[0] != P:
        raise ValueError("eigenvector rows do not match the design "
                         f"({V.shape[0]} vs {P}) — retrain to export")
    k = V.shape[1]
    V_ref = V[perm]                                    # cats-first rows

    cat_offsets = [0]
    for i in cats_i:
        cat_offsets.append(cat_offsets[-1] + len(blocks[i]))
    nnums = len(nums_i)
    if str(model.transform).lower() == "standardize":
        norm_sub = [float(m) for m in model.di_stats["num_means"]]
        # constant columns have sigma 0: emit 1.0 like the reference
        # (DataInfo.java:620 `sigma != 0 ? 1/sigma : 1`)
        norm_mul = [1.0 / float(s) if float(s) != 0.0 else 1.0
                    for s in model.di_stats["num_sigmas"]]
    else:
        norm_sub = [0.0] * nnums
        norm_mul = [1.0] * nnums

    names = [feats[i] for i in cats_i] + [feats[i] for i in nums_i]
    domains: List[Optional[List[str]]] = \
        [list(domains_by_feat[i]) for i in cats_i] + [None] * nnums
    info = _base_info(model, category="DimReduction", n_features=len(names),
                      n_classes=k, n_columns=len(names),
                      n_domains=len(cats_i))
    info.update({
        "mojo_version": "1.00",
        "algo": "pca",
        "algorithm": "Principal Component Analysis",
        "use_all_factor_levels": "true" if keep_all else "false",
        "pca_methods": str(model.params.get("pca_method", "GramSVD")),
        "pca_impl": str(model.params.get("pca_impl", "mtj_evd_symmmatrix")),
        "k": k,
        # names are emitted cats-first, so the row→cats-first map is id
        "permutation": _jarr(list(range(len(names)))),
        "ncats": len(cats_i),
        "nnums": nnums,
        "normSub": _jarr(norm_sub),
        "normMul": _jarr(norm_mul),
        "catOffsets": _jarr(cat_offsets),
        "eigenvector_size": P,
    })
    blobs = [("eigenvectors_raw", V_ref.astype(">f8").tobytes())]
    return _emit_mojo_zip(path, info, names, domains, blobs)


def score_reference_pca_mojo(path: str, rows: Dict[str, np.ndarray]):
    """Independent PCA scorer following PCAMojoModel.score0 exactly:
    cat levels index one-hot eigenvector rows (NaN/unseen skipped),
    nums project as (x - normSub) * normMul * eigenrow."""
    info, columns, domain_spec = _read_ini(path)
    k = int(info["k"])
    ncats = int(info["ncats"])
    nnums = int(info["nnums"])
    cat_offsets = [int(v) for v in _parse_jarr(info["catOffsets"])]
    permutation = [int(v) for v in _parse_jarr(info["permutation"])]
    norm_sub = np.asarray(_parse_jarr(info["normSub"]))
    norm_mul = np.asarray(_parse_jarr(info["normMul"]))
    use_all = str(info["use_all_factor_levels"]).lower() == "true"
    P = int(info["eigenvector_size"])
    with zipfile.ZipFile(path) as z:
        ev = np.frombuffer(z.read("eigenvectors_raw"),
                           dtype=">f8").reshape(P, k)
    domains = {columns[ci]: lv for ci, lv in domain_spec.items()}
    luts = {c: {s: j for j, s in enumerate(lv)}
            for c, lv in domains.items()}
    n = len(next(iter(rows.values())))
    out = np.zeros((n, k))
    for r in range(n):
        row = []
        for c in columns:
            v = rows[c][r]
            if c in luts and not (isinstance(v, float) and np.isnan(v)):
                row.append(float(luts[c].get(str(v), np.nan)))
            else:
                row.append(float(v) if v is not None else np.nan)
        acc = np.zeros(k)
        for j in range(ncats):
            tmp = row[permutation[j]]
            if np.isnan(tmp):
                continue
            last_cat = cat_offsets[j + 1] - cat_offsets[j] - 1
            level = int(tmp) - (0 if use_all else 1)
            if level < 0 or level > last_cat:
                continue
            acc += ev[cat_offsets[j] + level]
        vcol = cat_offsets[ncats]
        for j in range(nnums):
            acc += (row[permutation[ncats + j]] - norm_sub[j]) \
                * norm_mul[j] * ev[vcol + j]
        out[r] = acc
    return out


# ----------------------------------------- TargetEncoder writer/reader


def write_reference_te_mojo(model, path: str) -> str:
    """Reference-layout TargetEncoder MOJO
    (hex/genmodel/algos/targetencoder/TargetEncoderMojoReader.java:13):
    per-column ``[col]`` sections of ``level = num den`` lines in
    feature_engineering/target_encoding/encoding_map.ini (last index =
    the reserved NA level), the three columns-mapping ini files, and
    with_blending/inflection_point/smoothing kv. The reader recomputes
    priors as sum(num)/sum(den) per column (EncodingMap.getPriorMean),
    so the NA row is emitted as ``0 0`` — our fit excludes NA rows from
    the level stats and encodes NA with the prior, which the reader
    reproduces via te_column_name_to_missing_values_presence = 0."""
    p = model.params
    enc_cols = list(model.enc_maps.keys())
    emap_lines = []
    hasna_lines = []
    inenc_lines = []
    inout_lines = []
    for col in enc_cols:
        m = model.enc_maps[col]
        num = np.asarray(m["sum"], np.float64).sum(axis=0)
        den = np.asarray(m["cnt"], np.float64).sum(axis=0)
        emap_lines.append(f"[{col}]")
        for lv in range(len(num)):
            nv, dv = float(num[lv]), float(den[lv])
            nr = repr(int(nv)) if nv == int(nv) else repr(nv)
            dr = repr(int(dv)) if dv == int(dv) else repr(dv)
            emap_lines.append(f"{lv} = {nr} {dr}")
        emap_lines.append(f"{len(num)} = 0 0")      # reserved NA level
        hasna_lines.append(f"{col} = 0")
        inenc_lines += ["[from]", col, "[to]", col]
        inout_lines += ["[from]", col, "[to]", f"{col}_te"]

    names = list(enc_cols)
    domains: List[Optional[List[str]]] = \
        [list(model.enc_maps[c]["domain"]) for c in enc_cols]
    info = _base_info(model, category="TargetEncoder",
                      n_features=len(names), n_classes=1,
                      n_columns=len(names), n_domains=len(names))
    blending = bool(p.get("blending", False))
    info.update({
        "mojo_version": "1.00",
        "algo": "targetencoder",
        "algorithm": "TargetEncoder",
        "with_blending": "true" if blending else "false",
        "keep_original_categorical_columns": "true",
        "non_predictors": model.output.get("response") or "",
    })
    if blending:
        info["inflection_point"] = float(p.get("inflection_point", 10.0))
        info["smoothing"] = float(p.get("smoothing", 20.0))
    base = "feature_engineering/target_encoding"
    blobs = [
        (f"{base}/encoding_map.ini", "\n".join(emap_lines) + "\n"),
        (f"{base}/te_column_name_to_missing_values_presence.ini",
         "\n".join(hasna_lines) + "\n"),
        (f"{base}/input_encoding_columns_map.ini",
         "\n".join(inenc_lines) + "\n"),
        (f"{base}/input_output_columns_map.ini",
         "\n".join(inout_lines) + "\n"),
    ]
    return _emit_mojo_zip(path, info, names, domains,
                          [(n, b.encode()) for n, b in blobs])


def score_reference_te_mojo(path: str, rows: Dict[str, np.ndarray]):
    """Independent TE scorer following TargetEncoderMojoModel.score0:
    posterior num/den per level, optional lambda-blended toward the
    per-column prior sum(num)/sum(den); NA/unseen → prior (our export
    flags no NA level). Returns {col_te: [n]}."""
    info, columns, domain_spec = _read_ini(path)
    blending = str(info.get("with_blending", "false")).lower() == "true"
    ip = float(info.get("inflection_point", 10.0))
    sm = float(info.get("smoothing", 20.0))
    with zipfile.ZipFile(path) as z:
        emap_txt = z.read("feature_engineering/target_encoding/"
                          "encoding_map.ini").decode().splitlines()
        hasna_txt = z.read(
            "feature_engineering/target_encoding/"
            "te_column_name_to_missing_values_presence.ini"
        ).decode().splitlines()
    has_na = {}
    for ln in hasna_txt:
        if "=" in ln:
            k, _, v = ln.partition("=")
            has_na[k.strip()] = v.strip() == "1"
    emaps: Dict[str, Dict[int, tuple]] = {}
    sec = None
    for ln in emap_txt:
        ln = ln.strip()
        if not ln:
            continue
        if ln.startswith("[") and ln.endswith("]"):
            sec = ln[1:-1]
            emaps[sec] = {}
        else:
            k, _, v = ln.partition("=")
            num, den = v.strip().split(" ")
            emaps[sec][int(k)] = (float(num), float(den))
    domains = {columns[ci]: lv for ci, lv in domain_spec.items()}
    out = {}
    for col, emap in emaps.items():
        prior_num = sum(nd[0] for nd in emap.values())
        prior_den = sum(nd[1] for nd in emap.values())
        prior = prior_num / prior_den
        lv = {s: j for j, s in enumerate(domains[col])}
        vals = rows[col]
        enc = np.empty(len(vals))
        for r, v in enumerate(vals):
            isna = v is None or (isinstance(v, float) and np.isnan(v))
            cat = lv.get(str(v)) if not isna else None
            if cat is None and has_na.get(col):
                cat = max(emap)                     # reserved NA level
            if cat is None or cat not in emap or emap[cat][1] == 0:
                enc[r] = prior
                continue
            num, den = emap[cat]
            post = num / den
            if blending:
                lam = 1.0 / (1.0 + np.exp((ip - den) / sm))
                post = lam * post + (1.0 - lam) * prior
            enc[r] = post
        out[f"{col}_te"] = enc
    return out
