"""MOJO export — serialize trained in-cluster models to the offline format.

Reference: per-algo *MojoWriter classes (hex/tree/gbm/GbmMojoWriter etc.)
invoked from Model.getMojo; here one dispatch over the live model object.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np


def _tree_artifacts(model) -> Tuple[dict, Dict[str, np.ndarray]]:
    """Shared forest + binning serialization for SharedTree models."""
    bm = model.bm
    f = model.forest
    arrays = {
        "tree_feat": np.asarray(f.feat),
        "tree_thresh": np.asarray(f.thresh),
        "tree_na_left": np.asarray(f.na_left),
        "tree_is_split": np.asarray(f.is_split),
        "tree_leaf": np.asarray(f.leaf),
        "tree_leaf_w": np.asarray(f.leaf_w),
        "tree_cat_split": np.asarray(f.cat_split),
        "tree_left_words": np.asarray(f.left_words),
        "edges": np.asarray(bm.edges),
        "nbins": np.asarray(bm.nbins),
        "is_cat": np.asarray(bm.is_cat),
    }
    meta = {
        "nbins_total": int(bm.nbins_total),
        "feature_domains": [list(d) if d is not None else None
                            for d in bm.domains],
    }
    return meta, arrays


def _base_meta(model) -> dict:
    out = model.output
    return {
        "algo": model.algo,
        "category": out.get("category"),
        "names": list(out.get("names") or []),
        "response": out.get("response"),
        "domain": out.get("domain"),
        "nclasses": out.get("nclasses", 1),
        "default_threshold": out.get("default_threshold", 0.5),
    }


def mojo_artifacts(model) -> Tuple[dict, Dict[str, np.ndarray]]:
    algo = model.algo
    meta = _base_meta(model)
    if algo in ("gbm", "drf", "isolationforest"):
        tmeta, arrays = _tree_artifacts(model)
        meta.update(tmeta)
        if algo == "gbm":
            meta["f0"] = (np.asarray(model.f0).tolist())
            meta["distribution"] = model.dist_name
            meta["tweedie_power"] = float(model.params.get("tweedie_power", 1.5))
        elif algo == "isolationforest":
            meta["c_norm"] = float(model.c_norm)
            # training-frame path-length extrema: the in-cluster scorer
            # normalizes with (max - total) / (max - min) when these are
            # present (models/isofor.py _score_raw) — the MOJO must ship
            # them or its reader falls back to 2^(-ml/c) and diverges
            for stat in ("min_path_length", "max_path_length"):
                if model.output.get(stat) is not None:
                    meta[stat] = int(model.output[stat])
        return meta, arrays
    if algo == "glm":
        meta["link"] = model.family.link
        meta["family"] = model.family.name
        meta["tweedie_power"] = float(getattr(model.family, "p", 1.5))
        meta["standardize"] = bool(model.params.get("standardize", True))
        meta["use_all_factor_levels"] = bool(
            model.params.get("use_all_factor_levels", False))
        meta["names"] = list(model.features)
        meta["feature_domains"] = [list(d) if d is not None else None
                                   for d in model.di_stats["domains"]]
        arrays = {
            "num_means": np.asarray(model.di_stats["num_means"]),
            "num_sigmas": np.asarray(model.di_stats["num_sigmas"]),
        }
        if model.coef_multinomial is not None:
            arrays["coef_multinomial"] = np.asarray(model.coef_multinomial)
        else:
            arrays["coef"] = np.asarray(model.coef)
        return meta, arrays
    if algo == "deeplearning":
        meta["activation"] = model.act
        meta["standardize"] = bool(model.standardize)
        meta["use_all_factor_levels"] = bool(
            model.params.get("use_all_factor_levels", False))
        meta["autoencoder"] = bool(model.params.get("autoencoder", False))
        meta["n_layers"] = len(model.net)
        meta["names"] = list(model.features)
        meta["feature_domains"] = [list(d) if d is not None else None
                                   for d in model.di_stats["domains"]]
        if model.resp_stats is not None:
            meta["resp_stats"] = [float(model.resp_stats[0]),
                                  float(model.resp_stats[1])]
        arrays = {
            "num_means": np.asarray(model.di_stats["num_means"]),
            "num_sigmas": np.asarray(model.di_stats["num_sigmas"]),
        }
        for i, layer in enumerate(model.net):
            arrays[f"W{i}"] = np.asarray(layer["W"])
            arrays[f"b{i}"] = np.asarray(layer["b"])
        return meta, arrays
    if algo in ("pca", "svd"):
        meta["standardize"] = model.transform == "standardize"
        meta["use_all_factor_levels"] = bool(model.use_all_levels)
        meta["names"] = list(model.features)
        meta["feature_domains"] = [list(d) if d is not None else None
                                   for d in model.di_stats["domains"]]
        arrays = {
            "num_means": np.asarray(model.di_stats["num_means"]),
            "num_sigmas": np.asarray(model.di_stats["num_sigmas"]),
        }
        if algo == "pca":
            arrays["eigvecs"] = np.asarray(model.eigvecs)
        else:
            arrays["v"] = np.asarray(model.V)
            arrays["d"] = np.asarray(model.output["d"])
        return meta, arrays
    if algo == "isotonicregression":
        meta["out_of_bounds"] = str(model.params.get("out_of_bounds",
                                                     "clip"))
        arrays = {"thresholds_x": np.asarray(model.tx),
                  "thresholds_y": np.asarray(model.ty)}
        return meta, arrays
    if algo == "coxph":
        meta["names"] = list(model.features)
        meta["feature_domains"] = [list(d) if d is not None else None
                                   for d in model.di_stats["domains"]]
        meta["standardize"] = False
        meta["use_all_factor_levels"] = False
        meta["eta_mean"] = float(model.output["eta_mean"])
        arrays = {
            "coef": np.asarray(model.coef),
            "num_means": np.asarray(model.di_stats["num_means"]),
            "num_sigmas": np.asarray(model.di_stats["num_sigmas"]),
        }
        return meta, arrays
    if algo == "naivebayes":
        s = model.stats
        meta["num_names"] = list(s["num_names"])
        meta["cat_names"] = list(s["cat_names"])
        meta["cat_domains"] = [list(d) for d in s["cat_domains"]]
        meta["min_sdev"] = float(model.params.get("min_sdev") or 1e-3)
        meta["eps_sdev"] = float(model.params.get("eps_sdev") or 0.0)
        meta["min_prob"] = float(model.params.get("min_prob") or 1e-3)
        arrays = {"priors": np.asarray(s["priors"]),
                  "num_mu": np.asarray(s["num_mu"]),
                  "num_sd": np.asarray(s["num_sd"])}
        for j, tab in enumerate(s["cat_tables"]):
            arrays[f"cat_table_{j}"] = np.asarray(tab)
        return meta, arrays
    if algo == "upliftdrf":
        tmeta, arrays = _tree_artifacts(model)
        meta.update(tmeta)
        arrays["leaf_pt"] = np.asarray(model.leaf_pt)
        arrays["leaf_pc"] = np.asarray(model.leaf_pc)
        return meta, arrays
    if algo == "extendedisolationforest":
        meta["names"] = list(model.features)
        meta["c_norm"] = float(model.c_norm)
        f = model.forest
        arrays = {"ext_normals": np.asarray(f.normals),
                  "ext_offsets": np.asarray(f.offsets),
                  "ext_is_split": np.asarray(f.is_split),
                  "ext_leaf": np.asarray(f.leaf),
                  "col_means": np.asarray(model.means)}
        return meta, arrays
    if algo == "glrm":
        meta["standardize"] = model.transform == "standardize"
        meta["use_all_factor_levels"] = True
        meta["names"] = list(model.features)
        meta["feature_domains"] = [list(d) if d is not None else None
                                   for d in model.di_stats["domains"]]
        arrays = {
            "archetypes": np.asarray(model.Y),
            "num_means": np.asarray(model.di_stats["num_means"]),
            "num_sigmas": np.asarray(model.di_stats["num_sigmas"]),
        }
        return meta, arrays
    if algo == "word2vec":
        meta["vocab"] = list(model.vocab)
        arrays = {"vectors": np.asarray(model.vectors)}
        return meta, arrays
    if algo == "kmeans":
        meta["standardize"] = bool(model.standardize)
        meta["use_all_factor_levels"] = True
        meta["names"] = list(model.features)
        meta["feature_domains"] = [list(d) if d is not None else None
                                   for d in model.di_stats["domains"]]
        arrays = {
            "centers": np.asarray(model.centers_std),
            "num_means": np.asarray(model.di_stats["num_means"]),
            "num_sigmas": np.asarray(model.di_stats["num_sigmas"]),
        }
        return meta, arrays
    if algo == "rulefit":
        # composite MOJO: per-depth rule forests + the sparse GLM head
        # (reference hex/rulefit RuleFitMojoWriter bundles both parts)
        glm_meta, glm_arrays = mojo_artifacts(model.glm_model)
        meta["glm"] = glm_meta
        meta["rules"] = [{"model": r["model"], "tree": int(r["tree"]),
                          "lo": int(r["lo"]), "hi": int(r["hi"]),
                          "name": r["name"]} for r in model.rules]
        meta["linear_cols"] = list(model.linear_cols)
        meta["winsor"] = {n: [float(lo), float(hi)]
                          for n, (lo, hi) in model.winsor.items()}
        meta["n_tree_models"] = len(model.tree_models)
        arrays = {f"glm_{k}": v for k, v in glm_arrays.items()}
        for i, tm in enumerate(model.tree_models):
            tmeta, tarrays = _tree_artifacts(tm)
            meta[f"tm{i}_nbins_total"] = tmeta["nbins_total"]
            meta[f"tm{i}_feature_domains"] = tmeta["feature_domains"]
            meta[f"tm{i}_names"] = list(tm.bm.names)
            arrays.update({f"tm{i}_{k}": v for k, v in tarrays.items()})
        return meta, arrays
    raise ValueError(f"MOJO export not supported for algo '{algo}'")
