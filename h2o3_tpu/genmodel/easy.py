"""EasyPredictModelWrapper — labeled, typed single-row predictions.

Reference: hex/genmodel/easy/EasyPredictModelWrapper.java + the typed
prediction classes (BinomialModelPrediction, RegressionModelPrediction,
...) under hex/genmodel/easy/prediction/.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from h2o3_tpu.genmodel.readers import MojoModel


@dataclass
class BinomialModelPrediction:
    label: str
    label_index: int
    class_probabilities: List[float]


@dataclass
class MultinomialModelPrediction:
    label: str
    label_index: int
    class_probabilities: List[float]


@dataclass
class RegressionModelPrediction:
    value: float


@dataclass
class ClusteringModelPrediction:
    cluster: int


@dataclass
class AnomalyDetectionPrediction:
    score: float
    normalized_score: float = 0.0


class EasyPredictModelWrapper:
    """Row-dict in, typed prediction out."""

    def __init__(self, model: MojoModel):
        self.model = model

    def _score(self, row: dict) -> dict:
        return self.model.score0(row)

    def predict(self, row: dict):
        cat = self.model.category
        if cat == "Binomial":
            return self.predict_binomial(row)
        if cat == "Multinomial":
            return self.predict_multinomial(row)
        if cat == "Clustering":
            return self.predict_clustering(row)
        if cat == "AnomalyDetection":
            return self.predict_anomaly_detection(row)
        return self.predict_regression(row)

    def predict_binomial(self, row: dict) -> BinomialModelPrediction:
        out = self._score(row)
        idx = int(out["predict"])
        dom = self.model.domain or ["0", "1"]
        return BinomialModelPrediction(
            label=dom[idx], label_index=idx,
            class_probabilities=[float(out["p0"]), float(out["p1"])])

    def predict_multinomial(self, row: dict) -> MultinomialModelPrediction:
        out = self._score(row)
        idx = int(out["predict"])
        dom = self.model.domain or [str(i) for i in range(self.model.nclasses)]
        probs = [float(out[f"p{k}"]) for k in range(self.model.nclasses)]
        return MultinomialModelPrediction(label=dom[idx], label_index=idx,
                                          class_probabilities=probs)

    def predict_regression(self, row: dict) -> RegressionModelPrediction:
        return RegressionModelPrediction(value=float(self._score(row)["predict"]))

    def predict_clustering(self, row: dict) -> ClusteringModelPrediction:
        return ClusteringModelPrediction(cluster=int(self._score(row)["predict"]))

    def predict_anomaly_detection(self, row: dict) -> AnomalyDetectionPrediction:
        out = self._score(row)
        return AnomalyDetectionPrediction(score=float(out["predict"]),
                                          normalized_score=float(out["predict"]))

    def predict_contributions(self, row: dict) -> Dict[str, float]:
        """Per-feature TreeSHAP contributions + BiasTerm
        (EasyPredictModelWrapper.predictContributions role)."""
        batch = {k: np.asarray([v]) for k, v in row.items()}
        out = self.model.predict_contributions(batch)
        return {k: float(v[0]) for k, v in out.items()}
