"""Host-partitioned ingest coordination — the control plane of
pod-global sharded training (README §Distributed training).

Reference: in H2O a parsed dataset's chunks home on the node that read
them (water/parser/ParseDataset distributes chunks round-robin; a Vec
never materializes fully on one node). Here each process ingests ONLY
its ``mesh.owned_rows()`` slice of the source, and the codec decisions
that the replicated path makes from the full host array (dtype
narrowing, categorical interning — frame/column.py column_from_numpy)
are instead agreed over the coordination-service KV store in one
exchange round: every process publishes its local facts, reads every
peer's, and applies the deterministic merge. The merged decision is
bit-identical to what a single process would pick from the concatenated
rows, which is what the global-fit bit-parity guarantee rests on.

All entry points are COLLECTIVE: every process must call them at the
same point in program order (like the SPMD fit itself). The KV exchange
is out-of-band control-plane traffic — never a device collective — so a
dead peer surfaces as a bounded barrier timeout, not a wedged psum.
"""

from __future__ import annotations

import itertools
import json
import pickle
from typing import Dict, List

import numpy as np

KV_PREFIX = "h2o3tpu_ingest/"

# monotonic per-process exchange id: collective call order is identical
# on every process, so equal counters name the same exchange (and
# barrier ids never repeat within one coordination-service incarnation)
_SEQ = itertools.count()

# exact keys this process published — swept at cloud.shutdown() so a
# reformed cloud never reads a previous incarnation's ingest metadata
_PUBLISHED: List[str] = []


def _client():
    from jax._src import distributed
    return distributed.global_state.client


def _timeout_ms() -> int:
    from h2o3_tpu.core.config import ARGS
    return int(max(float(getattr(ARGS, "cloud_timeout_s", 120.0)), 1.0)
               * 1000)


def sweep_local_keys(client) -> None:
    """Delete this process's published ingest keys (shutdown hook)."""
    for key in _PUBLISHED:
        try:
            client.key_value_delete(key)
        except Exception:   # noqa: BLE001 - absent key / service down
            pass
    _PUBLISHED.clear()


def exchange_ingest_meta(local_meta: dict) -> List[dict]:
    """One collective JSON exchange: publish this process's per-column
    ingest facts, barrier, read every peer's. Returns the metas in
    process order. Single process: no traffic, ``[local_meta]``."""
    import jax
    nproc = jax.process_count()
    if nproc == 1:
        return [local_meta]
    client = _client()
    seq = next(_SEQ)
    pid = jax.process_index()
    prefix = f"{KV_PREFIX}meta/{seq}/"
    key = f"{prefix}{pid}"
    client.key_value_set(key, json.dumps(local_meta), allow_overwrite=True)
    _PUBLISHED.append(key)
    client.wait_at_barrier(f"h2o3tpu_ingest_meta_{seq}", _timeout_ms())
    metas: List[dict] = [None] * nproc  # type: ignore[list-item]
    for k, v in client.key_value_dir_get(prefix):
        metas[int(k.rsplit("/", 1)[-1])] = json.loads(v)
    missing = [i for i, m in enumerate(metas) if m is None]
    if missing:
        raise RuntimeError(
            f"partitioned ingest: no metadata from processes {missing}")
    return metas


def allgather_rows(arrays: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Gather every process's row slices into full host columns, in
    process (= row) order — the ``H2O3TPU_GLOBAL_FIT=off`` devolution
    path back to the legacy fully-replicated ingest. Control-plane only
    (pickled blobs over the KV store, chunked like the scheduler's
    work-item blobs) so it works on clouds without device collectives
    for host-object columns."""
    import jax
    nproc = jax.process_count()
    if nproc == 1:
        return {k: np.asarray(v) for k, v in arrays.items()}
    from h2o3_tpu.parallel.scheduler import _B64_CHUNK, _decode, _encode
    client = _client()
    seq = next(_SEQ)
    pid = jax.process_index()
    prefix = f"{KV_PREFIX}gather/{seq}/"
    b64 = _encode(pickle.dumps({k: np.asarray(v)
                                for k, v in arrays.items()}))
    nparts = (len(b64) + _B64_CHUNK - 1) // _B64_CHUNK
    mine: List[str] = []        # this exchange's keys, deleted below
    for j in range(nparts):
        key = f"{prefix}{pid}/p{j}"
        client.key_value_set(key, b64[j * _B64_CHUNK:(j + 1) * _B64_CHUNK],
                             allow_overwrite=True)
        _PUBLISHED.append(key)
        mine.append(key)
    meta_key = f"{prefix}{pid}/meta"
    client.key_value_set(meta_key, json.dumps({"parts": nparts}),
                         allow_overwrite=True)
    _PUBLISHED.append(meta_key)
    mine.append(meta_key)
    client.wait_at_barrier(f"h2o3tpu_ingest_gather_{seq}", _timeout_ms())
    out: Dict[str, np.ndarray] = {}
    for p in range(nproc):
        meta = json.loads(client.blocking_key_value_get(
            f"{prefix}{p}/meta", _timeout_ms()))
        parts = [client.blocking_key_value_get(f"{prefix}{p}/p{j}",
                                               _timeout_ms())
                 for j in range(int(meta["parts"]))]
        block = pickle.loads(_decode("".join(parts)))
        if not out:
            out = {k: [v] for k, v in block.items()}
        else:
            for k, v in block.items():
                out[k].append(v)
    # the blobs are dead the moment every peer has read them: second
    # barrier (all reads done), then delete this exchange's keys NOW —
    # otherwise each off-mode ingest leaves dataset-sized blobs (×nproc)
    # resident in the coordination service until cloud shutdown, and
    # _PUBLISHED grows without bound across ingests. The shutdown sweep
    # stays as the backstop for exchanges that die between the barriers.
    client.wait_at_barrier(f"h2o3tpu_ingest_gather_done_{seq}",
                           _timeout_ms())
    for key in mine:
        try:
            client.key_value_delete(key)
        except Exception:   # noqa: BLE001 - absent key / service down
            pass
    done = set(mine)
    _PUBLISHED[:] = [k for k in _PUBLISHED if k not in done]
    return {k: np.concatenate(vs) if len(vs) > 1 else vs[0]
            for k, vs in out.items()}


# ------------------------------------------------------------------ facts

def local_numeric_facts(values: np.ndarray) -> dict:
    """The per-process half of the numeric codec decision
    (column_from_numpy's narrowing), publishable as JSON. ``integral``
    mirrors the replicated path's test exactly: every clean value
    integral AND |v| < 2**31."""
    vals64 = np.asarray(values).astype(np.float64)
    clean = np.where(~np.isfinite(vals64), 0.0, vals64)
    n = clean.size
    return {
        "kind": "num",
        "integral": bool(np.all(clean == np.round(clean))
                         and np.all(np.abs(clean) < 2 ** 31)),
        "lo": float(clean.min()) if n else None,
        "hi": float(clean.max()) if n else None,
    }


def merge_numeric_facts(metas: List[dict]) -> dict:
    """Deterministic merge of per-process numeric facts — equals the
    facts a single process computes from the concatenated rows (empty
    local slices publish lo/hi None and drop out, matching numpy's
    ``min() if n else 0`` convention on the replicated path)."""
    los = [m["lo"] for m in metas if m["lo"] is not None]
    his = [m["hi"] for m in metas if m["hi"] is not None]
    return {"integral": all(m["integral"] for m in metas),
            "lo": min(los) if los else 0.0,
            "hi": max(his) if his else 0.0}


def local_str_levels(values: np.ndarray) -> List[str]:
    """Sorted unique string levels of this process's rows (None/NaN
    excluded — pandas factorize drops them on the replicated path)."""
    import pandas as pd
    _, uniques = pd.factorize(np.asarray(values, dtype=object), sort=True)
    return [str(u) for u in uniques]


def merge_str_levels(metas: List[dict]) -> List[str]:
    """Sorted union of per-process levels == pd.factorize(sort=True)
    uniques over the concatenated rows."""
    levels = set()
    for m in metas:
        levels.update(m["levels"])
    return sorted(levels)


def local_num_levels(values: np.ndarray) -> dict:
    """Unique raw values of a numeric column forced categorical — kept
    numeric (not stringified) so the merged union sorts numerically and
    the final ``str(u)`` formatting reproduces the replicated path's
    ``pd.factorize(sort=True)`` domain byte-for-byte."""
    import pandas as pd
    v = np.asarray(values)
    _, uniques = pd.factorize(v, sort=True)
    return {"kind": "cat_num", "levels": [u.item() for u in uniques],
            "dtype": str(v.dtype)}


def merge_num_levels(metas: List[dict]) -> np.ndarray:
    """Sorted union of raw numeric levels in the source dtype."""
    dtypes = {m["dtype"] for m in metas}
    if len(dtypes) > 1:
        raise ValueError(
            f"partitioned ingest: peers disagree on column dtype {dtypes}")
    levels = set()
    for m in metas:
        levels.update(m["levels"])
    return np.asarray(sorted(levels), dtype=np.dtype(dtypes.pop()))
