"""RollupStats — lazily computed, cached per-column summary statistics.

Reference: water/fvec/RollupStats.java:30-40 — min/max/mean/sigma/NA
count/zero count + histogram, computed by an MRTask sweep on first access
and cached on the Vec. Here: one jitted masked reduction per column,
cached on the Column; the reduce over the data mesh axis is the psum that
replaces the rollup MRTask's node tree.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from h2o3_tpu.frame.column import Column, T_STR


@jax.jit
def _rollup_kernel(x: jax.Array, na: jax.Array) -> dict:
    valid = ~na
    w = valid.astype(jnp.float32)
    n = jnp.sum(w)
    xf = x.astype(jnp.float32)
    xz = jnp.where(valid, xf, 0.0)
    s = jnp.sum(xz)
    mean = s / jnp.maximum(n, 1.0)
    ss = jnp.sum(jnp.where(valid, (xf - mean) ** 2, 0.0))
    big = jnp.float32(jnp.inf)
    return {
        "rows": n,
        "na_count": jnp.sum(na.astype(jnp.int32)),
        "mean": mean,
        "sigma": jnp.sqrt(ss / jnp.maximum(n - 1.0, 1.0)),
        "min": jnp.min(jnp.where(valid, xf, big)),
        "max": jnp.min(jnp.where(valid, -xf, big)) * -1.0,
        "zero_count": jnp.sum(jnp.where(valid, (x == 0).astype(jnp.float32), 0.0)),
        "sum": s,
    }


def prefetch_rollups(cols) -> None:
    """Fill many columns' rollup caches with ONE device→host fetch.

    N sequential rollups() calls block on N tunnel round trips (~10-100ms
    each on a remote-attached chip); a 1000-column frame summary
    (pyunit_create_frame shape) pays ~100s that way. Dispatch every
    column's kernel asynchronously, then device_get the whole list."""
    todo = [c for c in cols
            if c._rollups is None and c.type != T_STR and c.data is not None]
    if not todo:
        return
    fetched = jax.device_get([_rollup_kernel(c.data, c.na_mask)
                              for c in todo])
    for c, stats in zip(todo, fetched):
        out = {k: float(v) for k, v in stats.items()}
        out["rows"] = int(out["rows"])
        n_padding = c.data.shape[0] - c.nrows
        out["na_count"] = int(out["na_count"]) - n_padding
        out["zero_count"] = int(out["zero_count"])
        c._rollups = out


def rollups(col: Column) -> dict:
    """Compute-once stats (RollupStats.get semantics)."""
    if col._rollups is not None:
        return col._rollups
    if col.type == T_STR or col.data is None:
        col._rollups = {"rows": col.nrows, "na_count": 0}
        return col._rollups
    prefetch_rollups([col])
    return col._rollups
