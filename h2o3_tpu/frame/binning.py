"""Feature binning for histogram tree algorithms.

Reference: hex/tree/DHistogram.java:48 — per-column histograms with
min/maxEx ranges, nbins for numeric and nbins_cats for categoricals, NAs
tracked separately (DHistogram NA bucket). TPU-native: binning is done
ONCE up front into an int8/int32 [N, F] matrix (the quantile-sketch
"hist" approach the reference adopts from XGBoost in its xgboost
extension), so every tree level is pure integer compare/matmul work on
device and histogram shapes stay static.

Layout per feature f with ``nb[f]`` real bins: bin ids 0..nb[f]-1 hold
values, bin id B-1 (shared max) holds NAs; unused ids between are empty
and never win a split because their counts are zero.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.telemetry import observed_jit


@observed_jit("frame.bin_device")
@partial(jax.jit, static_argnames=("B", "is_cat_t", "has_remap_t",
                                   "div_t"))
def _bin_device(datas, nas, remaps, edges, *, B: int, is_cat_t: tuple,
                has_remap_t: tuple, div_t: tuple):
    """All columns → one [Npad, F] int32 bin matrix in ONE compiled
    program (the per-column eager version re-dispatched ~6 ops/column
    through the runtime, dominating cold parse+train time)."""
    cols = []
    for i, is_cat in enumerate(is_cat_t):
        na = nas[i]
        if is_cat:
            code = datas[i].astype(jnp.int32)
            if has_remap_t[i]:
                code = remaps[i][jnp.clip(code, 0, remaps[i].shape[0] - 1)]
                na = na | (code < 0)
                code = jnp.maximum(code, 0)
            # cardinality beyond nbins_cats: ADJACENT codes group into
            # one bin (integer divide — the reference DHistogram's
            # grouped categorical binning), never a modulo alias that
            # collides arbitrary levels (round-2 VERDICT miss #1)
            b = code // div_t[i] if div_t[i] > 1 else code
            b = jnp.where(na, B - 1, b)
        else:
            x = jnp.where(na, jnp.nan, datas[i].astype(jnp.float32))
            # bin = #edges <= x; vectorized compare-reduce (MXU-friendly,
            # no gather) — the hot loop of ScoreBuildHistogram2's bin()
            b = jnp.sum((x[:, None] >= edges[i][None, :]).astype(jnp.int32),
                        axis=1)
            b = jnp.where(na, B - 1, b)
        # int8 bins when they fit (B<=127 always holds for the default
        # 64-bin histograms): 4x less HBM for the [Npad, F] matrix, the
        # single largest tree-training resident at north-star scale
        cols.append(b.astype(jnp.int8 if B <= 127 else jnp.int32))
    return jnp.stack(cols, axis=1)


@dataclasses.dataclass(frozen=True)
class BinTileView:
    """Bin-major tiled view of a BinnedMatrix — the layout contract the
    Pallas tree kernels (ops/pallas/treekernel.py) stream through VMEM,
    and the device-direct ingest target of ROADMAP item 2.

    ``bins`` is the matrix row-padded to a whole number of tiles:
    feature-major int8 lanes (one lane per feature, bin ids along it),
    ``rows`` sublanes per tile, with the NA lane folded in as bin id
    ``nbins_total - 1`` — no separate NA mask rides with the tiles.
    Padding rows hold bin 0 and must be paired with zero-weight stats,
    exactly like mesh padding rows."""
    bins: jax.Array            # [ntiles*rows, F]
    rows: int                  # sublane extent of one tile
    ntiles: int
    nbins_total: int           # NA lane = nbins_total - 1, folded in

    @property
    def tile_shape(self):
        return (self.rows, self.bins.shape[1])


@dataclasses.dataclass
class BinnedMatrix:
    """Device-resident binned design matrix for tree building/scoring."""
    bins: jax.Array            # [Npad, F] int8/int32; NA = nbins_total-1
    nbins: jax.Array           # [F] int32 real bins per feature (excl. NA bin)
    edges: jax.Array           # [F, B-2] float32 split thresholds, +inf padded
    is_cat: np.ndarray         # [F] bool (host)
    names: List[str]
    nbins_total: int           # B = max real bins + 1 (NA)
    nrows: int
    domains: List[Optional[List[str]]]
    nbins_cats: int = 64       # cat-bin cap used at train time
    source_ref: Optional[object] = None  # weakref to the built-from frame
    _tile_cache: dict = dataclasses.field(default_factory=dict,
                                          repr=False, compare=False)

    @property
    def nfeatures(self) -> int:
        return len(self.names)

    def tile_view(self, rows: Optional[int] = None) -> BinTileView:
        """Bin-major tile view (cached per ``rows``): the matrix padded
        to whole [rows, F] tiles for VMEM streaming. ``rows=None`` picks
        the VMEM-sized suggestion for this matrix's (F, B) at a 32-node
        level (ops/pallas.vmem_tile_rows)."""
        if rows is None:
            from h2o3_tpu.ops.pallas import vmem_tile_rows
            rows = vmem_tile_rows(max(self.nfeatures, 1),
                                  self.nbins_total, 32)
        rows = max(1, min(int(rows), self.bins.shape[0]))
        tv = self._tile_cache.get(rows)
        if tv is None:
            n = self.bins.shape[0]
            ntiles = -(-n // rows)
            bins = self.bins
            if ntiles * rows != n:
                import jax.numpy as jnp
                bins = jnp.pad(bins, ((0, ntiles * rows - n), (0, 0)))
            tv = BinTileView(bins=bins, rows=rows, ntiles=ntiles,
                             nbins_total=self.nbins_total)
            self._tile_cache[rows] = tv
        return tv

    def __getstate__(self):
        # weakrefs don't pickle (model save/load path); the rebin
        # short-circuit simply doesn't survive serialization, and tile
        # views are cheap to rebuild
        d = dict(self.__dict__)
        d["source_ref"] = None
        d["_tile_cache"] = {}
        return d


def _numeric_edges(x: np.ndarray, nbins: int,
                   method: str = "quantiles",
                   w: Optional[np.ndarray] = None) -> np.ndarray:
    """Bin edges over valid values. method='quantiles' is the
    QuantilesGlobal histogram type (hex/tree/SharedTree; default hist
    behavior of the reference's XGBoost extension); 'uniform' is the
    equal-width UniformAdaptive type (hex/tree/DHistogram.java min/maxEx
    range binning) — required by IsolationForest, whose random thresholds
    must be uniform over the VALUE range, not the rank space.

    Quantile edges come from the WEIGHTED cdf over distinct values, with
    each cut placed at the midpoint between adjacent distinct values.
    This makes binning exactly invariant under the reference's row-weight
    contract (pyunit_weights_gbm): weight=k ≡ k duplicated rows, weight=0
    ≡ row removed, uniform weights ≡ no weights — properties plain
    np.quantile over raw rows does NOT have (zero-weight rows would shift
    edges). Midpoint cuts also never coincide with a data value, so a
    row's bin is insensitive to float rounding of the edge itself."""
    finite = np.isfinite(x)
    v = x[finite]
    wv = None
    if w is not None:
        wv = np.asarray(w, dtype=np.float64)[finite]
        pos = wv > 0
        v, wv = v[pos], wv[pos]
    if v.size == 0:
        return np.zeros((0,), dtype=np.float32)
    if method == "uniform":
        lo, hi = float(v.min()), float(v.max())
        if hi <= lo:
            return np.zeros((0,), dtype=np.float32)
        return np.linspace(lo, hi, nbins + 1)[1:-1].astype(np.float32)
    if method == "random":
        # XRT (extremely randomized trees): random split thresholds over
        # the value range (DRFStepsProvider XRT / DHistogram Random type)
        lo, hi = float(v.min()), float(v.max())
        if hi <= lo:
            return np.zeros((0,), dtype=np.float32)
        rng = np.random.RandomState(abs(hash((lo, hi))) % (2**31))
        return np.sort(rng.uniform(lo, hi, nbins - 1)).astype(np.float32)
    if v.size > 200_000:  # sketch on a sample, like the reference's ExactQuantilesToUse cap
        rng = np.random.RandomState(0xC0FFEE)
        idx = rng.randint(0, v.size, 200_000)
        v = v[idx]
        wv = None if wv is None else wv[idx]
    u, inv = np.unique(v, return_inverse=True)
    if u.size < 2:
        return np.zeros((0,), dtype=np.float32)
    wu = np.bincount(inv, weights=wv, minlength=u.size) if wv is not None \
        else np.bincount(inv, minlength=u.size).astype(np.float64)
    cdf = np.cumsum(wu)
    cdf /= cdf[-1]
    qs = np.linspace(0.0, 1.0, nbins + 1)[1:-1]
    # first distinct value whose cumulative weight reaches q; cut after it
    idx = np.searchsorted(cdf, qs, side="left")
    idx = idx[idx < u.size - 1]
    mids = (u[idx].astype(np.float64) + u[idx + 1]) * 0.5
    return np.unique(mids.astype(np.float32))


def bin_frame(frame: Frame, features: Sequence[str], nbins: int = 64,
              nbins_cats: int = 64,
              edges_override: Optional[List[np.ndarray]] = None,
              nbins_total_override: Optional[int] = None,
              train_domains: Optional[List[Optional[List[str]]]] = None,
              histogram_type: str = "quantiles",
              weights: Optional[np.ndarray] = None) -> BinnedMatrix:
    """Bin ``features`` of ``frame`` into a device int matrix.

    ``edges_override``/``train_domains`` re-bin a scoring frame with
    training-time edges and categorical domains — the adaptTestForTrain
    path (hex/Model.java:1850): unseen test levels map to the NA bin.
    ``weights`` (host [nrows]) makes the quantile sketch weighted so the
    row-weight ≡ row-multiplicity contract holds (see _numeric_edges).

    Training-path results are CACHED on the Frame keyed by (features,
    nbins, nbins_cats, histogram_type, weights digest) and invalidated
    on column mutation like the PR 4 ``Frame.device_matrix`` cache —
    grid/AutoML sweeps bin the same frame once per model-family config
    instead of once per fit. Scoring rebins (edges/domain overrides)
    bypass the cache: their key is the training matrix, not the frame.
    """
    F = len(features)
    names = list(features)
    cache_key = cache = None
    if (edges_override is None and nbins_total_override is None
            and train_domains is None):
        # weights enter the quantile sketch, so equal-CONTENT weights
        # must share a cache slot (every fit rebuilds the host mirror
        # array); a content digest is ~10ms at 5M rows vs seconds of
        # re-binning
        if weights is None:
            wdig = None
        else:
            import hashlib
            warr = np.ascontiguousarray(np.asarray(weights, np.float64))
            wdig = hashlib.blake2b(warr.tobytes(),
                                   digest_size=16).hexdigest()
        cache_key = (tuple(names), int(nbins), int(nbins_cats),
                     str(histogram_type), wdig)
        cache = getattr(frame, "_bin_cache", None)
        if cache is None:
            cache = {}
            try:
                frame._bin_cache = cache
            except Exception:   # noqa: BLE001 - exotic frame stand-ins
                cache = None
        if cache is not None and cache_key in cache:
            return cache[cache_key]
    cols = [frame.col(n) for n in names]
    is_cat = np.array([c.is_categorical for c in cols], dtype=bool)
    domains = [c.domain for c in cols]

    # per-feature edges / cardinalities (host, once); batch the
    # device→host fetches of every numeric column into one round trip
    if edges_override is None:
        from h2o3_tpu.frame.column import prefetch_host
        prefetch_host([c for i, c in enumerate(cols) if not is_cat[i]])
    edge_list: List[np.ndarray] = []
    nb = np.zeros((F,), dtype=np.int32)
    div = np.ones((F,), dtype=np.int32)   # code→bin divisor (card>nbins_cats)
    for i, c in enumerate(cols):
        if is_cat[i]:
            if train_domains is not None and train_domains[i] is not None:
                card = max(len(train_domains[i]), 1)
            else:
                card = max(c.cardinality, 1)
            if card > nbins_cats:
                div[i] = -(-card // nbins_cats)   # ceil
                nb[i] = -(-card // div[i])
            else:
                nb[i] = card
            edge_list.append(np.zeros((0,), dtype=np.float32))
        else:
            if edges_override is not None:
                e = edges_override[i]
            else:
                e = _numeric_edges(c.to_numpy(), nbins, histogram_type,
                                   w=weights)
            nb[i] = len(e) + 1
            edge_list.append(e)

    # B is part of the STATIC jit key (TreeParams.nbins_total), so it
    # must depend only on the binning CONFIG, never the data: a fold
    # frame whose numeric columns happen to have fewer distinct values
    # than nbins would otherwise get a smaller B and force a fresh XLA
    # compile per fold (the round-2 cv/grid 600s timeouts). Unused bin
    # ids have zero counts and never win a split.
    B = max(int(nbins), int(nb.max()) if F else 1) + 1  # +1 shared NA bin
    if nbins_total_override is not None:
        B = nbins_total_override
    # fixed edge-matrix width for the same reason (its shape is static
    # in _bin_device's program)
    emax = max(nbins - 1, max((len(e) for e in edge_list), default=0))
    edges = np.full((F, max(emax, 1)), np.inf, dtype=np.float32)
    for i, e in enumerate(edge_list):
        edges[i, : len(e)] = e

    sharding = cols[0].data.sharding if cols else None
    edges_dev = jax.device_put(edges)
    nb_dev = jax.device_put(nb)

    # one jitted pass over all columns (retraces per frame schema only)
    datas, nas, remaps = [], [], []
    has_remap = []
    for i, c in enumerate(cols):
        datas.append(c.data)
        nas.append(c.na_mask)
        if is_cat[i] and train_domains is not None \
                and train_domains[i] is not None \
                and c.domain != train_domains[i]:
            lut = {lvl: j for j, lvl in enumerate(train_domains[i])}
            mapping = np.array([lut.get(lvl, -1) for lvl in (c.domain or [])],
                               dtype=np.int32)
            if len(mapping) == 0:
                mapping = np.array([-1], dtype=np.int32)
            remaps.append(jnp.asarray(mapping))
            has_remap.append(True)
        else:
            remaps.append(jnp.zeros((1,), jnp.int32))
            has_remap.append(False)
    if F:
        bins = _bin_device(tuple(datas), tuple(nas), tuple(remaps),
                           edges_dev, B=B, is_cat_t=tuple(bool(v) for v in is_cat),
                           has_remap_t=tuple(has_remap),
                           div_t=tuple(int(v) for v in div))
    else:
        bins = jnp.zeros((frame.nrows_padded, 0), jnp.int32)
    if sharding is not None:
        from h2o3_tpu.parallel.mesh import row_sharding
        from h2o3_tpu.parallel.mesh import put_sharded
        bins = put_sharded(bins, row_sharding())

    import weakref
    try:
        src_ref = weakref.ref(frame)
    except TypeError:
        src_ref = None
    bm = BinnedMatrix(bins=bins, nbins=nb_dev, edges=edges_dev,
                      is_cat=is_cat, names=names, nbins_total=B,
                      nrows=frame.nrows, domains=domains,
                      nbins_cats=nbins_cats, source_ref=src_ref)
    if cache is not None and cache_key is not None:
        cache[cache_key] = bm
    return bm


def rebin_for_scoring(train_bm: BinnedMatrix, frame: Frame) -> BinnedMatrix:
    """Bin a new frame with the training matrix's edges/domains.

    Scoring the SAME frame object the matrix was built from returns it
    as-is — CV fold models share the parent frame and the parent bin
    edges, so a rebin per fold (hundreds in near-LOO sweeps) would redo
    identical work. Identity is by weakref (a mutated/replaced frame is
    a new object and rebins normally)."""
    ref = getattr(train_bm, "source_ref", None)
    if ref is not None and ref() is frame:
        return train_bm
    host_edges = np.asarray(train_bm.edges)
    per_feat = []
    for i in range(train_bm.nfeatures):
        e = host_edges[i]
        per_feat.append(e[np.isfinite(e)])
    return bin_frame(frame, train_bm.names,
                     nbins=train_bm.nbins_total - 1,
                     nbins_cats=train_bm.nbins_cats,
                     edges_override=per_feat,
                     nbins_total_override=train_bm.nbins_total,
                     train_domains=train_bm.domains)
