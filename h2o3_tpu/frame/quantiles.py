"""Distributed quantiles — iterative histogram refinement on device.

Reference: hex/quantile/Quantile.java:15 — per-column pass builds a
histogram over the value range, identifies the bin containing the target
rank, re-histograms inside that bin, repeats until exact
(iterative-refinement; combine methods interpolate/average/low/high).

TPU-native: each refinement round is one segment_sum over 1024 bins
(psum across the mesh); 3 rounds resolve ~2^30 distinct values. All
probs for a column share rounds (vectorized over the quantile axis).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.ops.segments import segment_sum
from h2o3_tpu.parallel.mesh import get_mesh

NBINS = 1024


def _hist_pass(x, w, lo, hi):
    """Weighted histogram of x within [lo, hi] per quantile row.

    lo/hi: [Q]. Returns counts [Q, NBINS]."""
    Q = lo.shape[0]
    width = jnp.maximum(hi - lo, 1e-30)
    outs = []
    for q in range(Q):
        b = jnp.clip(((x - lo[q]) / width[q] * NBINS).astype(jnp.int32),
                     0, NBINS - 1)
        inrange = (x >= lo[q]) & (x <= hi[q])
        outs.append(segment_sum(b, (w * inrange)[:, None], n_nodes=NBINS,
                                mesh=get_mesh())[:, 0])
    return jnp.stack(outs)


def _values_at_ranks(x0, w, ranks: np.ndarray, gmin: float, gmax: float,
                     rounds: int) -> np.ndarray:
    """Exact k-th order statistics by bracket refinement: after each round
    the bracket [lo, hi] containing rank k shrinks ×NBINS; `rounds`=4
    resolves any float32 value exactly (range/2^40 < eps)."""
    Q = len(ranks)
    lo = jnp.full((Q,), gmin)
    hi = jnp.full((Q,), gmax)
    base = np.zeros(Q)            # weight strictly below lo
    for _ in range(rounds):
        hist = np.asarray(_hist_pass(x0, w, lo, hi))
        lo_h, hi_h = np.asarray(lo, np.float64), np.asarray(hi, np.float64)
        width = np.maximum(hi_h - lo_h, 1e-30) / NBINS
        cum = np.cumsum(hist, axis=1)
        new_lo, new_hi, new_base = [], [], []
        for q in range(Q):
            r = ranks[q] - base[q]
            k = int(np.searchsorted(cum[q], r, side="right"))
            k = min(k, NBINS - 1)
            below = cum[q][k - 1] if k > 0 else 0.0
            new_lo.append(lo_h[q] + k * width[q])
            new_hi.append(lo_h[q] + (k + 1) * width[q])
            new_base.append(base[q] + below)
        lo = jnp.asarray(new_lo, jnp.float32)
        hi = jnp.asarray(new_hi, jnp.float32)
        base = np.asarray(new_base)
    return (np.asarray(lo, np.float64) + np.asarray(hi, np.float64)) / 2.0


def column_quantiles(col, probs: Sequence[float], rounds: int = 4,
                     combine_method: str = "interpolate") -> np.ndarray:
    """Quantiles of one numeric Column at the given probs.

    combine_method (reference QuantileModel.CombineMethod): how to combine
    the two neighboring order statistics when the target rank is
    fractional — interpolate (default) / average / low / high.

    Small columns take an exact f64 host sort (to_numpy populates the
    host cache if cold — DETERMINISTIC, not dependent on earlier cache
    warming): the reference computes in f64 and the pyunits assert
    1e-6 absolute agreement with numpy, which the device's f32
    bisection can miss.
    """
    host = (col.to_numpy()
            if col.nrows <= 4_000_000 and col.type == "numeric" else None)
    if host is not None:
        v = np.sort(host[~np.isnan(host)])
        if v.size == 0:
            return np.full(len(probs), np.nan)
        probs = np.asarray(probs, np.float64)
        ranks = probs * (v.size - 1.0)
        klo = np.floor(ranks).astype(int)
        khi = np.ceil(ranks).astype(int)
        vlo, vhi = v[klo], v[khi]
        method = combine_method.lower()
        if method == "low":
            return vlo
        if method == "high":
            return vhi
        if method in ("average", "avg", "mean"):
            return (vlo + vhi) / 2.0
        return vlo + (ranks - klo) * (vhi - vlo)
    x = col.numeric_view()
    valid = ~jnp.isnan(x)
    w = valid.astype(jnp.float32)
    # padding rows are NaN in numeric_view, so w covers them
    x0 = jnp.where(valid, x, 0.0)
    total = float(jnp.sum(w))
    if total == 0:
        return np.full(len(probs), np.nan)
    gmin = float(jnp.min(jnp.where(valid, x, jnp.inf)))
    gmax = float(jnp.max(jnp.where(valid, x, -jnp.inf)))
    probs = np.asarray(probs, np.float64)
    # target rank (0-based, type-7 scheme, Quantile.java interpolation)
    ranks = probs * (total - 1.0)
    klo = np.floor(ranks)
    khi = np.ceil(ranks)
    uniq = np.unique(np.concatenate([klo, khi]))
    vals = _values_at_ranks(x0, w, uniq, gmin, gmax, rounds)
    at = dict(zip(uniq.tolist(), vals))
    vlo = np.array([at[k] for k in klo])
    vhi = np.array([at[k] for k in khi])
    method = combine_method.lower()
    if method == "low":
        return vlo
    if method == "high":
        return vhi
    if method in ("average", "avg", "mean"):
        return (vlo + vhi) / 2.0
    frac = ranks - klo
    return vlo + frac * (vhi - vlo)   # interpolate


def frame_quantiles(frame, probs: Sequence[float] = (0.01, 0.1, 0.25, 0.333,
                                                     0.5, 0.667, 0.75, 0.9,
                                                     0.99),
                    combine_method: str = "interpolate"):
    """Quantile table for all numeric columns (the h2o.quantile surface,
    water/rapids AstQtile)."""
    out = {"probs": np.asarray(probs)}
    for name in frame.names:
        c = frame.col(name)
        if c.is_categorical or c.type == "string":
            continue
        out[name] = column_quantiles(c, probs, combine_method=combine_method)
    return out
