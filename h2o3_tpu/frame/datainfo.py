"""DataInfo — row-wise design-matrix view with one-hot + standardization.

Reference: hex/DataInfo.java:16 — GLM/DeepLearning/GLRM iterate rows
through a view that expands categoricals to indicator columns (skipping
the first level unless useAllFactorLevels), imputes NAs (mean imputation
default) and standardizes numerics. TPU-native: the expansion is
materialized once into a dense [Npad, P] f32 device matrix, row-sharded —
dense one-hot blocks are MXU fuel, and P stays modest for the tabular
regimes H2O targets (wide one-hot spaces are the one TP-style sharding
candidate, SURVEY §2.4 item 6).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.rollups import rollups
from h2o3_tpu.parallel.mesh import row_sharding


@partial(jax.jit, static_argnames=("spec", "standardize"))
def _design_device(datas, nas, stats, *, spec: tuple, standardize: bool):
    """All columns → the dense [Npad, P] design matrix in ONE compiled
    program. ``spec`` per column: ("cat", first_level, cardinality) or
    ("num",); ``stats`` per column: (mu, sd) scalars (unused for cats).
    """
    blocks = []
    for i, sp in enumerate(spec):
        na = nas[i]
        if sp[0] == "cat":
            _, first, card = sp
            code = datas[i].astype(jnp.int32)
            levels = jnp.arange(first, card, dtype=jnp.int32)
            oh = (code[:, None] == levels[None, :]).astype(jnp.float32)
            blocks.append(jnp.where(na[:, None], 0.0, oh))
        else:
            mu, sd = stats[i]
            x = datas[i].astype(jnp.float32)
            x = jnp.where(na | jnp.isnan(x), mu, x)   # mean imputation
            if standardize:
                x = (x - mu) / sd
            blocks.append(x[:, None])
    return jnp.concatenate(blocks, axis=1)


@dataclasses.dataclass
class DataInfo:
    names: List[str]                 # source columns
    coef_names: List[str]            # expanded coefficient names
    X: jax.Array                     # [Npad, P] design matrix (row-sharded)
    is_cat: np.ndarray
    cat_offsets: np.ndarray          # start index of each cat block
    num_means: np.ndarray            # imputation means of numeric cols
    num_sigmas: np.ndarray
    domains: List[Optional[List[str]]]
    standardize: bool
    use_all_factor_levels: bool
    nrows: int

    @property
    def P(self) -> int:
        return self.X.shape[1]


def build_datainfo(frame: Frame, features: Sequence[str],
                   standardize: bool = True,
                   use_all_factor_levels: bool = False,
                   missing_values_handling: str = "mean_imputation",
                   stats_override: Optional[dict] = None) -> DataInfo:
    """Expand ``features`` into the design matrix.

    ``stats_override`` carries training-time means/sigmas/domains when
    adapting a scoring frame (adaptTestForTrain role).
    """
    cols = [frame.col(n) for n in features]
    is_cat = np.array([c.is_categorical for c in cols], dtype=bool)
    coef_names: List[str] = []
    cat_offsets = []
    num_means, num_sigmas = [], []
    domains: List[Optional[List[str]]] = []
    shard = row_sharding()

    # host pass: names/domains/stats + per-column device inputs; the
    # expansion itself runs as ONE jitted program (_design_device) —
    # per-column eager ops re-dispatch through the runtime and dominate
    # wall time on a remote-attached chip
    datas, nas, stats, spec = [], [], [], []
    for i, c in enumerate(cols):
        if is_cat[i]:
            if stats_override is not None:
                dom = stats_override["domains"][i]
                from h2o3_tpu.models.model import adapt_domain
                codes = adapt_domain(c, dom)
                codes = np.pad(codes, (0, frame.nrows_padded - frame.nrows),
                               constant_values=-1)
                datas.append(jax.device_put(
                    np.maximum(codes, 0).astype(np.int32), shard))
                nas.append(jax.device_put(codes < 0, shard))
            else:
                dom = c.domain or []
                datas.append(c.data)
                nas.append(c.na_mask)
            domains.append(dom)
            first = 0 if use_all_factor_levels else 1
            card = max(len(dom), 1)
            cat_offsets.append(len(coef_names))
            # NA row: all-zero indicator block (majority-level impute would
            # also be valid; the reference's default is mean imputation which
            # for indicators is the level frequency — zero is the simple,
            # consistent choice and is masked by skip rows when requested)
            spec.append(("cat", first, card))
            stats.append((0.0, 1.0))
            coef_names += [f"{c.name}.{dom[l]}" for l in range(first, card)]
        else:
            domains.append(None)
            if stats_override is not None:
                mu = stats_override["num_means"][len(num_means)]
                sd = stats_override["num_sigmas"][len(num_sigmas)]
            else:
                r = rollups(c)
                mu, sd = r["mean"], (r["sigma"] or 1.0)
            num_means.append(mu)
            num_sigmas.append(sd if sd > 0 else 1.0)
            spec.append(("num",))
            stats.append((float(mu), float(sd if sd > 0 else 1.0)))
            datas.append(c.data)
            nas.append(c.na_mask)
            coef_names.append(c.name)

    if cols:
        X = _design_device(tuple(datas), tuple(nas),
                           tuple((jnp.float32(m), jnp.float32(s))
                                 for m, s in stats),
                           spec=tuple(spec), standardize=bool(standardize))
    else:
        X = jnp.zeros((frame.nrows_padded, 0), jnp.float32)
    X = jax.device_put(X, shard)
    return DataInfo(
        names=list(features), coef_names=coef_names, X=X, is_cat=is_cat,
        cat_offsets=np.asarray(cat_offsets, np.int64),
        num_means=np.asarray(num_means), num_sigmas=np.asarray(num_sigmas),
        domains=domains, standardize=standardize,
        use_all_factor_levels=use_all_factor_levels, nrows=frame.nrows)


def stats_of(di: DataInfo) -> dict:
    """Training stats needed to rebuild the view on a scoring frame."""
    return {"num_means": di.num_means, "num_sigmas": di.num_sigmas,
            "domains": di.domains}


def coef_stats(di: DataInfo):
    """Per-coefficient (mean, sd) aligned with coef_names — identity
    (0, 1) for one-hot indicator coefs, the standardization stats for
    numerics. Lets GLM report both standardized and de-standardized
    coefficients (hex/glm GLMModel coefficients_table)."""
    mus, sds = [], []
    ni = 0
    for i, cat in enumerate(di.is_cat):
        if cat:
            dom = di.domains[i] or []
            first = 0 if di.use_all_factor_levels else 1
            k = max(len(dom), 1) - first
            mus += [0.0] * k
            sds += [1.0] * k
        else:
            mus.append(float(di.num_means[ni]))
            sds.append(float(di.num_sigmas[ni]))
            ni += 1
    return np.asarray(mus), np.asarray(sds)
