"""Frame — named list of Columns, the distributed dataframe.

Reference: water/fvec/Frame.java:65 (~1960 LoC) — a Frame is a name→Vec
mapping living in the DKV; all columns share row count and chunk layout.
Here all columns share the padded row count and the mesh row-sharding, so
any subset of columns can enter one jitted kernel with aligned shards.

The lazy Rapids expression surface (h2o-py builds ASTs client-side,
h2o-py/h2o/expr.py) maps to the eager-but-jitted ops in
``h2o3_tpu.rapids``; Frame exposes the common munging verbs directly.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Union

import jax
import numpy as np

from h2o3_tpu.parallel.mesh import fetch_replicated as _fetch_np

from h2o3_tpu.core.kv import DKV, make_key
from h2o3_tpu.frame.column import Column, T_CAT, T_NUM, column_from_numpy
from h2o3_tpu.frame.rollups import rollups
from h2o3_tpu.parallel import mesh as mesh_mod


def _durability_on() -> bool:
    """One env read — the ``H2O3TPU_DATA_DURABILITY=off`` fast path
    stays a zero-overhead no-op (core/durability.py)."""
    return os.environ.get("H2O3TPU_DATA_DURABILITY", "off") != "off"


class Frame:
    def __init__(self, columns: List[Column], nrows: int, key: Optional[str] = None):
        self._cols: Dict[str, Column] = {c.name: c for c in columns}
        self._order: List[str] = [c.name for c in columns]
        # device_matrix cache: column-name tuple -> stacked [Npad, F]
        # device array (invalidated on column mutation)
        self._matrix_cache: Dict[tuple, jax.Array] = {}
        # bin_frame cache: (features, nbins, nbins_cats, hist type,
        # weights digest) -> BinnedMatrix (frame/binning.py) — same
        # mutation-invalidation contract as _matrix_cache, so grid/
        # AutoML sweeps stop re-binning the same frame per model family
        self._bin_cache: Dict[tuple, object] = {}
        self.nrows = nrows
        self.key = key or make_key("frame")
        DKV.put(self.key, self)
        if _durability_on():
            # lineage registration + mirror write-through (ISSUE 18);
            # transient frames construct under durability.suspended()
            from h2o3_tpu.core import durability
            durability.on_frame_put(self)

    # ---- construction ------------------------------------------------
    @staticmethod
    def from_numpy(arrays: Dict[str, np.ndarray],
                   categorical: Sequence[str] = (),
                   domains: Optional[Dict[str, List[str]]] = None,
                   strings: Sequence[str] = (),
                   uuids: Sequence[str] = (),
                   times: Sequence[str] = (),
                   key: Optional[str] = None,
                   block: int = 8,
                   pad_to: Optional[int] = None) -> "Frame":
        """Build a Frame from host columns (upload path, POST /3/ParseSetup).

        ``categorical`` forces listed columns to T_CAT; ``domains`` supplies
        pre-interned level lists for integer-coded categorical columns;
        ``strings`` keeps listed columns as host-side T_STR (no interning
        — the CStrChunk role, never entering math paths). ``pad_to``
        forces at least that padded row count — CV fold frames pad to the
        parent frame's shape so one compiled program serves every fold.
        """
        from h2o3_tpu.frame.column import Column, T_STR, T_UUID
        names = list(arrays.keys())
        n = len(next(iter(arrays.values()))) if names else 0
        npad = mesh_mod.padded_rows(n, block=block)
        if pad_to is not None:
            npad = max(npad, int(pad_to))
        shard = mesh_mod.row_sharding()
        cols = []
        for name in names:
            v = np.asarray(arrays[name])
            if name in strings or name in uuids:
                cols.append(Column(
                    name=name,
                    type=T_UUID if name in uuids else T_STR,
                    data=None, na_mask=None, nrows=n,
                    strings=v.astype(object)))
                continue
            dom = (domains or {}).get(name)
            if name in categorical and dom is None and v.dtype.kind not in "OUS":
                import pandas as pd
                codes, uniques = pd.factorize(v, sort=True)
                dom, v = [str(u) for u in uniques], codes.astype(np.int32)
            cols.append(column_from_numpy(name, v, npad, shard,
                                          domain=dom,
                                          time=name in times))
        return Frame(cols, n, key=key)

    @staticmethod
    def from_numpy_partitioned(arrays: Dict[str, np.ndarray],
                               nrows: int,
                               categorical: Sequence[str] = (),
                               domains: Optional[Dict[str, List[str]]] = None,
                               times: Sequence[str] = (),
                               key: Optional[str] = None,
                               block: int = 8,
                               pad_to: Optional[int] = None) -> "Frame":
        """Collective host-partitioned ingest (README §Distributed
        training): every process calls this at the same program point
        with ONLY its ``mesh.owned_rows(nrows, block=block)`` slice of
        each column, and the frame's device data comes up host-
        partitioned — no process's *devices* ever hold peer rows. (Each
        process does retain the full exact-f64 host-side view, seeded
        here by one batched allgather, so the collective-free host
        surface — REST handlers, scheduled items — works unchanged.) The
        codec decisions the replicated path makes from the full host
        array are agreed in one coordination-KV exchange
        (frame/partition.py), so the resulting global device bytes are
        identical to ``from_numpy`` over the concatenated rows.

        ``H2O3TPU_GLOBAL_FIT=off`` devolves to the legacy replicated
        layout (rows allgathered over the control plane, then
        ``from_numpy``). Single process: bit-identical to ``from_numpy``
        by construction. String/UUID columns are unsupported here — they
        are host-side objects that never enter math paths; ingest them
        replicated."""
        from h2o3_tpu.frame import partition as part_mod
        from h2o3_tpu.frame.column import (column_from_partitioned,
                                           seed_partitioned_host_caches)
        names = list(arrays.keys())
        nrows = int(nrows)
        nproc = jax.process_count()
        if not mesh_mod.global_fit_enabled() and nproc > 1:
            full = part_mod.allgather_rows(
                {n: np.asarray(arrays[n]) for n in names})
            return Frame.from_numpy(full, categorical=categorical,
                                    domains=domains, times=times, key=key,
                                    block=block, pad_to=pad_to)
        npad = mesh_mod.padded_rows(nrows, block=block)
        if pad_to is not None:
            npad = max(npad, int(pad_to))
        lo, hi = mesh_mod.partition_bounds(npad)
        if nproc > 1 and lo != jax.process_index() * (hi - lo):
            # gather_partitioned_host and owned_rows both assume process
            # p homes rows [p*L, (p+1)*L) — the process-major device
            # order every jax.distributed cloud builds
            raise ValueError(
                f"process {jax.process_index()} owns padded rows "
                f"[{lo}, {hi}) — not process-major row order")
        lo_c, hi_c = min(lo, nrows), min(hi, nrows)
        meta: Dict[str, Optional[dict]] = {}
        for name in names:
            v = np.asarray(arrays[name])
            if v.shape[0] != hi_c - lo_c:
                raise ValueError(
                    f"column {name!r}: got {v.shape[0]} rows; this "
                    f"process owns logical rows [{lo_c}, {hi_c})")
            if (domains or {}).get(name) is not None:
                meta[name] = None          # pre-interned: nothing to agree
            elif v.dtype == object or v.dtype.kind in "US":
                meta[name] = {"kind": "cat_str",
                              "levels": part_mod.local_str_levels(v)}
            elif name in categorical:
                meta[name] = part_mod.local_num_levels(v)
            else:
                meta[name] = part_mod.local_numeric_facts(v)
        metas = part_mod.exchange_ingest_meta(meta) if nproc > 1 else [meta]
        shard = mesh_mod.row_sharding()
        cols = []
        for name in names:
            v = np.asarray(arrays[name])
            dom = (domains or {}).get(name)
            facts = None
            per_col = [m[name] for m in metas]
            kind = None if per_col[0] is None else per_col[0]["kind"]
            if kind == "cat_str":
                dom = part_mod.merge_str_levels(per_col)
            elif kind == "cat_num":
                levels = part_mod.merge_num_levels(per_col)
                dom = [str(u) for u in levels]
                v64 = v.astype(np.float64)
                codes = np.searchsorted(levels, v.astype(levels.dtype))
                v = np.where(np.isfinite(v64), codes, -1).astype(np.int32)
            elif kind == "num":
                facts = part_mod.merge_numeric_facts(per_col)
            cols.append(column_from_partitioned(
                name, v, span=(lo, hi), nrows=nrows, npad=npad,
                sharding=shard, domain=dom, facts=facts,
                time=name in times))
        # seed every column's full f64 host view NOW, in one batched
        # allgather, while every process is provably at this collective
        # point — host_view()/prefetch_host() run in single-process
        # contexts (REST handlers, scheduled work items) that must never
        # issue a cross-process collective
        seed_partitioned_host_caches(cols)
        return Frame(cols, nrows, key=key)

    @staticmethod
    def from_blocks(accs: Dict[str, "object"], names: List[str],
                    nrows: int, key: Optional[str] = None,
                    block: int = 1) -> "Frame":
        """Assemble BlockAccumulator columns into a Frame — the shared
        block-assembly tail of the streamed-CSV and Arrow ingest paths.

        ``accs`` maps column name → frame.column.BlockAccumulator whose
        add_* calls already arrived in window order; each finish() runs
        the jitted on-device concat/upcast/pad assembly.
        """
        npad = mesh_mod.padded_rows(nrows, block=block)
        cols = [accs[nm].finish(nrows, npad) for nm in names]
        return Frame(cols, nrows, key=key)

    def rename_columns(self, new_names) -> "Frame":
        """In-place positional rename (h2o-py set_names / Parse
        column_names)."""
        assert len(new_names) == len(self._order)
        new_cols = {}
        for old, new in zip(list(self._order), new_names):
            c = self._cols.pop(old)
            c.name = new
            new_cols[new] = c
        self._cols = new_cols
        self._order = list(new_names)
        # name-keyed caches: stale after rename
        getattr(self, "_matrix_cache", {}).clear()
        getattr(self, "_bin_cache", {}).clear()
        # a mutated frame no longer matches its source file — the
        # Cleaner must not evict it back to a FileBackedFrame stub
        self._source_paths = None
        return self

    @staticmethod
    def from_pandas(df, key: Optional[str] = None) -> "Frame":
        import pandas.api.types as pt
        arrays = {}
        categorical = []
        for name in df.columns:
            s = df[name]
            if pt.is_numeric_dtype(s.dtype) or pt.is_bool_dtype(s.dtype):
                arrays[name] = s.to_numpy(dtype="float64", na_value=np.nan)
            elif pt.is_datetime64_any_dtype(s.dtype):
                arrays[name] = s.astype("int64").to_numpy().astype(np.float64)
            else:  # str / category / object → categorical via interning
                vals = s.astype("object").to_numpy()
                # keep missing as None so interning assigns code -1 (NA);
                # genuine "" strings stay a real level
                arrays[name] = np.array(
                    [None if v is None or (isinstance(v, float) and np.isnan(v))
                     else str(v) for v in vals], dtype=object)
                categorical.append(name)
        return Frame.from_numpy(arrays, categorical=categorical, key=key)

    # ---- structure ---------------------------------------------------
    @property
    def ncols(self) -> int:
        return len(self._order)

    @property
    def names(self) -> List[str]:
        return list(self._order)

    @property
    def shape(self):
        return (self.nrows, self.ncols)

    @property
    def nrows_padded(self) -> int:
        for c in self._cols.values():
            if c.data is not None:
                return c.data.shape[0]
        return self.nrows

    def col(self, name_or_idx: Union[str, int]) -> Column:
        if isinstance(name_or_idx, int):
            name_or_idx = self._order[name_or_idx]
        return self._cols[name_or_idx]

    def __getitem__(self, sel) -> "Frame":
        if isinstance(sel, (str, int)):
            sel = [sel]
        cols = [self.col(s) for s in sel]
        if _durability_on():
            from h2o3_tpu.core import durability
            with durability.suspended():
                fr = Frame(cols, self.nrows)
            # stamp the op chain BEFORE registering, so the registry
            # entry carries replayable lineage (core/durability.py)
            durability.record_derived(fr, "select", self,
                                      {"columns": [c.name for c in cols]})
            durability.on_frame_put(fr)
            return fr
        return Frame(cols, self.nrows)

    def __contains__(self, name: str) -> bool:
        return name in self._cols

    def add_column(self, col: Column) -> None:
        self._cols[col.name] = col
        if col.name not in self._order:
            self._order.append(col.name)
        getattr(self, "_matrix_cache", {}).clear()   # column set changed
        getattr(self, "_bin_cache", {}).clear()
        self._source_paths = None    # mutated: no source-stub eviction

    def drop(self, names: Sequence[str]) -> "Frame":
        keep = [self.col(n) for n in self._order if n not in set(names)]
        if _durability_on():
            from h2o3_tpu.core import durability
            with durability.suspended():
                fr = Frame(keep, self.nrows)
            durability.record_derived(fr, "drop", self,
                                      {"columns": sorted(set(names))})
            durability.on_frame_put(fr)
            return fr
        return Frame(keep, self.nrows)

    def row_slice(self, lo: int, hi: int) -> "Frame":
        """Transient sub-frame of rows ``[lo, hi)`` — the chunk view of
        the chunked bulk-predict path (models/model.py
        predict_in_chunks). Rebuilt from the cached host views (exact
        f64 values, so narrowing reproduces the parent's device bytes)
        and kept OUT of the DKV: callers score it and drop it."""
        from h2o3_tpu.frame.column import T_STR, T_TIME, T_UUID
        lo, hi = max(int(lo), 0), min(int(hi), self.nrows)
        arrays: Dict[str, np.ndarray] = {}
        domains: Dict[str, List[str]] = {}
        strings, uuids, times = [], [], []
        for n in self._order:
            c = self.col(n)
            if c.type in (T_STR, T_UUID):
                arrays[n] = c.strings[lo:hi]
                (uuids if c.type == T_UUID else strings).append(n)
                continue
            v = c.host_view()[lo:hi]
            if c.is_categorical:
                # float codes with NaN NAs → -1 (the NA code the
                # pre-interned-domain path expects)
                arrays[n] = np.where(np.isnan(v), -1.0, v)
                domains[n] = list(c.domain or [])
            else:
                arrays[n] = v
                if c.type == T_TIME:
                    times.append(n)
        if _durability_on():
            # transient view: suspend the write-through hook — scoring
            # chunks must not pay (or churn) the mirror
            from h2o3_tpu.core import durability
            with durability.suspended():
                fr = Frame.from_numpy(arrays, domains=domains,
                                      strings=strings, uuids=uuids,
                                      times=times)
        else:
            fr = Frame.from_numpy(arrays, domains=domains, strings=strings,
                                  uuids=uuids, times=times)
        DKV.remove(fr.key)     # transient view, never store-resident
        return fr

    def local_copy(self) -> "Frame":
        """Rebuild this frame on the CURRENT mesh from the cached host
        views — the scheduled-work-item input (parallel/scheduler.py).
        Called under ``mesh.local_mesh_scope()`` it yields a frame whose
        device arrays live only on this process's devices, built through
        the same from_numpy narrowing/padding a single-process ingest
        runs (the scheduler's bit-parity contract). Collective-free on
        multi-process clouds: column_from_numpy retained the host copies
        at ingest. Cached per device set; kept out of the DKV."""
        devs = tuple(str(d) for d in mesh_mod.get_mesh().devices.flat)
        cache = getattr(self, "_local_copies", None)
        if cache is None:
            cache = self._local_copies = {}
        fr = cache.get(devs)
        if fr is None:
            fr = self.row_slice(0, self.nrows)
            cache[devs] = fr
        return fr

    # ---- stats (RollupStats surface on the frame) --------------------
    def summary(self) -> Dict[str, dict]:
        from h2o3_tpu.frame.rollups import prefetch_rollups
        prefetch_rollups([self.col(n) for n in self._order])
        out = {}
        for n in self._order:
            c = self.col(n)
            s = dict(rollups(c))
            s["type"] = c.type
            if c.domain:
                s["cardinality"] = len(c.domain)
            out[n] = s
        return out

    def mean(self, name: str) -> float:
        return rollups(self.col(name))["mean"]

    def types(self) -> Dict[str, str]:
        return {n: self.col(n).type for n in self._order}

    # ---- materialization --------------------------------------------
    def to_pandas(self):
        import pandas as pd
        data = {}
        for n in self._order:
            c = self.col(n)
            v = c.to_numpy()
            if c.is_categorical and c.domain:
                dom = np.array(c.domain + [None], dtype=object)
                codes = _fetch_np(c.data)[: c.nrows].astype(np.int64)
                codes[_fetch_np(c.na_mask)[: c.nrows]] = len(c.domain)
                v = dom[codes]
            elif c.type == "numeric" and v.dtype.kind == "f" and \
                    v.size and not np.isnan(v).any() and \
                    np.all(v == np.round(v)) and \
                    np.max(np.abs(v), initial=0) < 2 ** 53:
                # integral columns download as ints (the reference's
                # CSV shows 4, not 4.0 — pyunit_table parses int())
                v = v.astype(np.int64)
            data[n] = v
        return pd.DataFrame(data)

    def device_matrix(self, names: Optional[Sequence[str]] = None) -> jax.Array:
        """Stacked [Npad, F] float32 device matrix, CACHED per
        column-name tuple: repeated grid/AutoML fits and predicts over
        the same feature set previously re-ran ``jnp.stack`` over every
        column on each call, re-materializing X in HBM each time. The
        cache invalidates on column mutation (add_column /
        rename_columns) — column data itself is an immutable device
        array, so name identity is sufficient."""
        import jax.numpy as jnp
        key = tuple(names) if names is not None else tuple(self._order)
        cache = getattr(self, "_matrix_cache", None)
        if cache is None:            # deserialized pre-cache instances
            cache = self._matrix_cache = {}
        m = cache.get(key)
        if m is None:
            m = jnp.stack([self.col(n).numeric_view() for n in key],
                          axis=1)
            cache[key] = m
        return m

    def matrix(self, names: Optional[Sequence[str]] = None) -> jax.Array:
        """Stack numeric views into a padded [Npad, F] float32 device matrix."""
        return self.device_matrix(names)

    def device_cache_nbytes(self) -> int:
        """Device bytes pinned by the derived caches (device_matrix
        stacks + bin_frame results) — what the memory governor charges
        this frame beyond its columns (core/memgov.py)."""
        from h2o3_tpu.core.memgov import _frame_cache_nbytes
        return _frame_cache_nbytes(self)

    def drop_device_caches(self) -> int:
        """Release the derived device caches; returns bytes freed. The
        OOM escalation ladder's eviction hook (core/memgov.py): these
        caches rebuild transparently on next use, so dropping them
        trades recompute for HBM under pressure."""
        freed = self.device_cache_nbytes()
        getattr(self, "_matrix_cache", {}).clear()
        getattr(self, "_bin_cache", {}).clear()
        return freed

    def valid_weights(self) -> jax.Array:
        """1.0 for logical rows, 0.0 for mesh-padding rows."""
        return mesh_mod.valid_mask(self.nrows, self.nrows_padded)

    def __repr__(self) -> str:
        return f"<Frame {self.key} {self.nrows}x{self.ncols} {self._order[:8]}>"
