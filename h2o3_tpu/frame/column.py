"""Column — the Vec analogue: one typed, distributed column.

Reference: water/fvec/Vec.java (distributed compressed column split into
Chunks, ~20 codec classes picked per chunk by NewChunk.compress,
water/fvec/NewChunk.java:1133). TPU-native replacement per SURVEY §7:
chunk codecs collapse into dtype-narrowed dense device arrays + an NA
bitmask + a categorical dictionary. Rows shard over the mesh 'data' axis;
padding rows (mesh alignment) are marked NA so every reduction that
honours the mask is exact.

Types (reference Vec.T_NUM/T_CAT/T_TIME/T_STR/T_UUID, water/fvec/Vec.java):
- numeric:     float32/float64/int narrowed device array
- categorical: int32 codes + host-side ``domain`` list (water/parser/
               Categorical.java interning becomes pandas factorize)
- time:        int64 epoch-millis device array
- string:      host-side numpy object array (never on device; the
               reference likewise keeps CStrChunk out of math paths)
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

T_NUM, T_CAT, T_TIME, T_STR = "numeric", "categorical", "time", "string"
T_UUID = "uuid"      # host-side 128-bit ids (C16Chunk role) — never in math


@dataclasses.dataclass
class Column:
    name: str
    type: str                        # T_NUM | T_CAT | T_TIME | T_STR
    data: Optional[jax.Array]        # device array, padded length; None for T_STR
    na_mask: Optional[jax.Array]     # bool device array, True = missing
    nrows: int                       # logical (unpadded) length
    domain: Optional[List[str]] = None   # categorical levels
    strings: Optional[np.ndarray] = None  # host strings for T_STR
    _rollups: Optional[dict] = None      # cached stats (RollupStats analogue)

    @property
    def is_numeric(self) -> bool:
        return self.type in (T_NUM, T_TIME)

    @property
    def is_categorical(self) -> bool:
        return self.type == T_CAT

    @property
    def is_uuid(self) -> bool:
        return self.type == T_UUID

    @property
    def cardinality(self) -> int:
        return len(self.domain) if self.domain else 0

    def numeric_view(self) -> jax.Array:
        """float32 view with NaN at NA positions — the math-path input.

        Analogue of Chunk.atd() returning NaN for missing
        (water/fvec/Chunk.java).
        """
        x = self.data.astype(jnp.float32)
        return jnp.where(self.na_mask, jnp.nan, x)

    def host_view(self) -> np.ndarray:
        """READ-ONLY cached host view, logical rows only, NaN/None NAs.

        Cached: columns are immutable (mutation makes new columns), and
        on a remote-attached chip every device→host fetch costs a full
        tunnel round trip (~100 ms) regardless of size — one batched
        fetch of (data, mask), then reuse. Callers must not mutate;
        use to_numpy() for an owned copy.
        """
        if self.type in (T_STR, T_UUID):
            return self.strings[: self.nrows]
        host = getattr(self, "_host_cache", None)
        if host is None:
            from h2o3_tpu.parallel.mesh import fetch_replicated
            data, mask = fetch_replicated((self.data, self.na_mask))
            x = data[: self.nrows].astype(np.float64)
            x[mask[: self.nrows]] = np.nan
            host = x
            object.__setattr__(self, "_host_cache", host)
        return host

    def to_numpy(self) -> np.ndarray:
        """Host copy of host_view() — callers may mutate their copy."""
        if self.type in (T_STR, T_UUID):
            return self.strings[: self.nrows].copy()
        return self.host_view().copy()


def prefetch_host(cols: List["Column"]) -> None:
    """Fill the host caches of many columns with ONE device→host fetch.

    N sequential to_numpy calls cost N tunnel round trips (~100 ms each
    on a remote-attached chip); jax.device_get on the whole pytree
    batches them into one transfer.
    """
    todo = [c for c in cols
            if c.type not in (T_STR, T_UUID)
            and getattr(c, "_host_cache", None) is None]
    if not todo:
        return
    from h2o3_tpu.parallel.mesh import fetch_replicated
    fetched = fetch_replicated([(c.data, c.na_mask) for c in todo])
    for c, (data, mask) in zip(todo, fetched):
        x = data[: c.nrows].astype(np.float64)
        x[mask[: c.nrows]] = np.nan
        object.__setattr__(c, "_host_cache", x)


def column_from_numpy(name: str, values: np.ndarray, nrows_padded: int,
                      sharding, domain: Optional[List[str]] = None,
                      time: bool = False) -> Column:
    """Build a Column from host data, narrowing dtype (codec selection).

    The reference picks a Chunk codec per 1K-1M-element chunk
    (NewChunk.compress); here one dtype per column: int8/int16/int32 for
    integral ranges, float32 otherwise, int32 codes for categoricals.
    """
    values = np.asarray(values)
    n = values.shape[0]
    pad = nrows_padded - n

    if values.dtype == object or values.dtype.kind in "US":
        if domain is None:
            # categorical via interning, domain sorted lexicographically
            # like the reference parser (water/parser/Categorical.java)
            import pandas as pd
            codes, uniques = pd.factorize(values, sort=True)
            domain = [str(u) for u in uniques]
            values = codes.astype(np.int32)
        else:
            # explicit domain: map labels to codes, unseen/None → NA
            lut = {lvl: i for i, lvl in enumerate(domain)}
            values = np.asarray([lut.get(v, -1) if v is not None else -1
                                 for v in values], np.int32)
        na = values < 0
        data = np.where(na, 0, values).astype(np.int32)
        ctype = T_CAT
    elif domain is not None:
        na = (values < 0) | ~np.isfinite(values.astype(np.float64))
        data = np.where(na, 0, values).astype(np.int32)
        ctype = T_CAT
    else:
        vals64 = values.astype(np.float64)
        na = ~np.isfinite(vals64)
        clean = np.where(na, 0.0, vals64)
        if np.all(clean == np.round(clean)) and np.all(np.abs(clean) < 2**31):
            lo, hi = clean.min() if n else 0, clean.max() if n else 0
            if -128 <= lo and hi <= 127:
                data = clean.astype(np.int8)
            elif -32768 <= lo and hi <= 32767:
                data = clean.astype(np.int16)
            else:
                data = clean.astype(np.int32)
        else:
            data = clean.astype(np.float32)
        ctype = T_NUM

    data = np.pad(data, (0, pad))
    na = np.pad(na, (0, pad), constant_values=True)  # padding rows are NA
    from h2o3_tpu.parallel.mesh import put_sharded
    if time and ctype == T_NUM:
        # Vec.T_TIME: epoch millis. Device storage remains f32 (x64 is
        # off under jit — int64 would silently truncate to int32), so
        # device math on times is ~65-131s-granular; all host paths
        # (rapids time ops, downloads) read the exact f64 cache below.
        ctype = T_TIME
    col = Column(
        name=name, type=ctype,
        data=put_sharded(data, sharding),
        na_mask=put_sharded(na, sharding),
        nrows=n, domain=domain)
    if ctype in (T_NUM, T_TIME) and data.dtype == np.float32:
        # seed the host cache with the ORIGINAL float64 values: the
        # munging/metadata path (rapids reducers, quantiles, mmult)
        # then matches f64 oracles exactly, while the device keeps the
        # f32 math-path copy. Same layout to_numpy would build.
        host64 = vals64.copy()
        host64[na[:n]] = np.nan
        object.__setattr__(col, "_host_cache", host64)
    return col
