"""Column — the Vec analogue: one typed, distributed column.

Reference: water/fvec/Vec.java (distributed compressed column split into
Chunks, ~20 codec classes picked per chunk by NewChunk.compress,
water/fvec/NewChunk.java:1133). TPU-native replacement per SURVEY §7:
chunk codecs collapse into dtype-narrowed dense device arrays + an NA
bitmask + a categorical dictionary. Rows shard over the mesh 'data' axis;
padding rows (mesh alignment) are marked NA so every reduction that
honours the mask is exact.

Types (reference Vec.T_NUM/T_CAT/T_TIME/T_STR/T_UUID, water/fvec/Vec.java):
- numeric:     float32/float64/int narrowed device array
- categorical: int32 codes + host-side ``domain`` list (water/parser/
               Categorical.java interning becomes pandas factorize)
- time:        int64 epoch-millis device array
- string:      host-side numpy object array (never on device; the
               reference likewise keeps CStrChunk out of math paths)
"""

from __future__ import annotations

import dataclasses
from functools import partial as _partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

T_NUM, T_CAT, T_TIME, T_STR = "numeric", "categorical", "time", "string"
T_UUID = "uuid"      # host-side 128-bit ids (C16Chunk role) — never in math


@dataclasses.dataclass
class Column:
    name: str
    type: str                        # T_NUM | T_CAT | T_TIME | T_STR
    data: Optional[jax.Array]        # device array, padded length; None for T_STR
    na_mask: Optional[jax.Array]     # bool device array, True = missing
    nrows: int                       # logical (unpadded) length
    domain: Optional[List[str]] = None   # categorical levels
    strings: Optional[np.ndarray] = None  # host strings for T_STR
    _rollups: Optional[dict] = None      # cached stats (RollupStats analogue)

    @property
    def is_numeric(self) -> bool:
        return self.type in (T_NUM, T_TIME)

    @property
    def is_categorical(self) -> bool:
        return self.type == T_CAT

    @property
    def is_uuid(self) -> bool:
        return self.type == T_UUID

    @property
    def cardinality(self) -> int:
        return len(self.domain) if self.domain else 0

    def numeric_view(self) -> jax.Array:
        """float32 view with NaN at NA positions — the math-path input.

        Analogue of Chunk.atd() returning NaN for missing
        (water/fvec/Chunk.java).
        """
        x = self.data.astype(jnp.float32)
        return jnp.where(self.na_mask, jnp.nan, x)

    def host_view(self) -> np.ndarray:
        """READ-ONLY cached host view, logical rows only, NaN/None NAs.

        Cached: columns are immutable (mutation makes new columns), and
        on a remote-attached chip every device→host fetch costs a full
        tunnel round trip (~100 ms) regardless of size — one batched
        fetch of (data, mask), then reuse. Callers must not mutate;
        use to_numpy() for an owned copy.
        """
        if self.type in (T_STR, T_UUID):
            return self.strings[: self.nrows]
        host = getattr(self, "_host_cache", None)
        if host is None:
            if getattr(self, "_part_cache", None) is not None:
                # host-partitioned columns get their host cache seeded
                # eagerly at ingest (seed_partitioned_host_caches — a
                # guaranteed collective point). Assembling it HERE would
                # require a cross-process device collective, and
                # host_view() runs in single-process contexts (REST
                # handlers, scheduled work items) whose contract forbids
                # collectives — so a missing cache is a bug, never
                # something to gather lazily.
                raise RuntimeError(
                    f"partitioned column {self.name!r} has no host cache;"
                    " it must be seeded at ingest"
                    " (seed_partitioned_host_caches)")
            from h2o3_tpu.parallel.mesh import fetch_replicated
            data, mask = fetch_replicated((self.data, self.na_mask))
            x = data[: self.nrows].astype(np.float64)
            x[mask[: self.nrows]] = np.nan
            host = x
            object.__setattr__(self, "_host_cache", host)
        return host

    def to_numpy(self) -> np.ndarray:
        """Host copy of host_view() — callers may mutate their copy."""
        if self.type in (T_STR, T_UUID):
            return self.strings[: self.nrows].copy()
        return self.host_view().copy()


def prefetch_host(cols: List["Column"]) -> None:
    """Fill the host caches of many columns with ONE device→host fetch.

    N sequential to_numpy calls cost N tunnel round trips (~100 ms each
    on a remote-attached chip); jax.device_get on the whole pytree
    batches them into one transfer.
    """
    todo = [c for c in cols
            if c.type not in (T_STR, T_UUID)
            and getattr(c, "_host_cache", None) is None]
    if not todo:
        return
    stale = [c.name for c in todo
             if getattr(c, "_part_cache", None) is not None]
    if stale:
        # see host_view(): partitioned host caches are seeded at ingest;
        # prefetch_host may run in single-process contexts, so it must
        # never assemble them here (that would take a collective)
        raise RuntimeError(
            f"partitioned columns {stale} have no host cache; they must "
            "be seeded at ingest (seed_partitioned_host_caches)")
    from h2o3_tpu.parallel.mesh import fetch_replicated
    fetched = fetch_replicated([(c.data, c.na_mask) for c in todo])
    for c, (data, mask) in zip(todo, fetched):
        x = data[: c.nrows].astype(np.float64)
        x[mask[: c.nrows]] = np.nan
        object.__setattr__(c, "_host_cache", x)


def column_from_numpy(name: str, values: np.ndarray, nrows_padded: int,
                      sharding, domain: Optional[List[str]] = None,
                      time: bool = False) -> Column:
    """Build a Column from host data, narrowing dtype (codec selection).

    The reference picks a Chunk codec per 1K-1M-element chunk
    (NewChunk.compress); here one dtype per column: int8/int16/int32 for
    integral ranges, float32 otherwise, int32 codes for categoricals.
    """
    values = np.asarray(values)
    n = values.shape[0]
    pad = nrows_padded - n

    if values.dtype == object or values.dtype.kind in "US":
        if domain is None:
            # categorical via interning, domain sorted lexicographically
            # like the reference parser (water/parser/Categorical.java)
            import pandas as pd
            codes, uniques = pd.factorize(values, sort=True)
            domain = [str(u) for u in uniques]
            values = codes.astype(np.int32)
        else:
            # explicit domain: map labels to codes, unseen/None → NA
            lut = {lvl: i for i, lvl in enumerate(domain)}
            values = np.asarray([lut.get(v, -1) if v is not None else -1
                                 for v in values], np.int32)
        na = values < 0
        data = np.where(na, 0, values).astype(np.int32)
        ctype = T_CAT
    elif domain is not None:
        na = (values < 0) | ~np.isfinite(values.astype(np.float64))
        data = np.where(na, 0, values).astype(np.int32)
        ctype = T_CAT
    else:
        vals64 = values.astype(np.float64)
        na = ~np.isfinite(vals64)
        clean = np.where(na, 0.0, vals64)
        if np.all(clean == np.round(clean)) and np.all(np.abs(clean) < 2**31):
            lo, hi = clean.min() if n else 0, clean.max() if n else 0
            if -128 <= lo and hi <= 127:
                data = clean.astype(np.int8)
            elif -32768 <= lo and hi <= 32767:
                data = clean.astype(np.int16)
            else:
                data = clean.astype(np.int32)
        else:
            data = clean.astype(np.float32)
        ctype = T_NUM

    data = np.pad(data, (0, pad))
    na = np.pad(na, (0, pad), constant_values=True)  # padding rows are NA
    from h2o3_tpu.parallel.mesh import put_sharded
    if time and ctype == T_NUM:
        # Vec.T_TIME: epoch millis. Device storage remains f32 (x64 is
        # off under jit — int64 would silently truncate to int32), so
        # device math on times is ~65-131s-granular; all host paths
        # (rapids time ops, downloads) read the exact f64 cache below.
        ctype = T_TIME
    col = Column(
        name=name, type=ctype,
        data=put_sharded(data, sharding),
        na_mask=put_sharded(na, sharding),
        nrows=n, domain=domain)
    if ctype in (T_NUM, T_TIME) and data.dtype == np.float32:
        # seed the host cache with the ORIGINAL float64 values: the
        # munging/metadata path (rapids reducers, quantiles, mmult)
        # then matches f64 oracles exactly, while the device keeps the
        # f32 math-path copy. Same layout to_numpy would build.
        host64 = vals64.copy()
        host64[na[:n]] = np.nan
        object.__setattr__(col, "_host_cache", host64)
    elif not getattr(sharding, "is_fully_addressable", True):
        # multi-process cloud: every process holds the same full host
        # copy at ingest (the put_sharded contract), so retain the host
        # view NOW — host_view() would otherwise have to allgather the
        # cross-process shards, and scheduled work items
        # (parallel/scheduler.py) must never issue a collective. One f64
        # host copy per column, multi-process clouds only.
        host64 = data[:n].astype(np.float64)
        host64[na[:n]] = np.nan
        object.__setattr__(col, "_host_cache", host64)
    return col


def gather_partitioned_host(slabs):
    """Assemble full host arrays from per-process partitioned slabs
    (pytree in, matching pytree of full arrays out). Process order IS
    row order — asserted by Frame.from_numpy_partitioned at ingest.
    Single process: the slab already covers every row.

    COLLECTIVE: multihost_utils.process_allgather is an SPMD *device*
    collective — every process must reach this call at the same program
    point, or the pod wedges until the cloud timeout. The only caller is
    seed_partitioned_host_caches under Frame.from_numpy_partitioned,
    which is collective by contract; never call this from a
    single-process context (REST handlers, scheduled work items).

    Slabs travel as raw BYTES (uint8 views, reinterpreted on arrival):
    pushing the f64 host slabs through jax directly would silently
    truncate them to f32 (x64 is off under jit), breaking the exact-f64
    host-view contract every oracle test pins."""
    import jax
    if jax.process_count() == 1:
        return slabs
    from jax.experimental import multihost_utils
    leaves, treedef = jax.tree_util.tree_flatten(slabs)
    as_bytes = [np.ascontiguousarray(v).view(np.uint8) for v in leaves]
    gathered = jax.device_get(
        multihost_utils.process_allgather(as_bytes, tiled=True))
    out = [np.asarray(g).view(v.dtype)
           for g, v in zip(gathered, leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


def seed_partitioned_host_caches(cols: List["Column"]) -> None:
    """Fill the host caches of host-partitioned columns with ONE batched
    slab allgather (exact f64 — the device arrays may be narrowed to
    f32). Called by Frame.from_numpy_partitioned, a guaranteed
    collective point, so later host_view()/prefetch_host() calls from a
    SINGLE process (REST handlers, scheduled work items — contexts whose
    contract forbids cross-process collectives) hit the cache and never
    need peer participation — the partitioned analogue of
    column_from_numpy's eager multi-process host-cache seed. Each
    process ends up holding the full f64 host view (same host-memory
    footprint as the replicated ingest); device data stays partitioned.
    """
    todo = [c for c in cols
            if getattr(c, "_part_cache", None) is not None
            and getattr(c, "_host_cache", None) is None]
    if not todo:
        return
    gathered = gather_partitioned_host([c._part_cache for c in todo])
    for c, full in zip(todo, gathered):
        object.__setattr__(c, "_host_cache", np.asarray(full)[: c.nrows])


def column_from_partitioned(name: str, values: np.ndarray, *,
                            span, nrows: int, npad: int, sharding,
                            domain: Optional[List[str]] = None,
                            facts: Optional[dict] = None,
                            time: bool = False) -> Column:
    """Host-partitioned complement of ``column_from_numpy``: ``values``
    holds ONLY this process's logical rows (global rows ``[span[0],
    min(span[1], nrows))``), every codec decision comes from the
    globally-merged ``facts``/``domain`` (frame/partition.py) — never
    from local data, or peers would pick divergent dtypes — and
    placement goes through ``put_partitioned`` so no process ever
    materializes a peer's rows. Bit-identical to ``column_from_numpy``
    on a single process, where the local slab is the whole column.
    """
    from h2o3_tpu.parallel.mesh import put_partitioned
    values = np.asarray(values)
    lo, hi = span
    local_n = values.shape[0]
    pad = (hi - lo) - local_n        # mesh-padding rows homed here
    vals64 = None

    if values.dtype == object or values.dtype.kind in "US":
        assert domain is not None, (
            "partitioned string-typed ingest requires the merged domain")
        lut = {lvl: i for i, lvl in enumerate(domain)}
        # str-coerce before the lookup: the merged domain holds str(u)
        # levels (partition.local_str_levels), so non-str objects in an
        # object column (ints/floats mixed with strings) must code
        # through their str form like the replicated auto-factorize
        # path — not silently become NA
        codes = np.asarray(
            [lut.get(v if isinstance(v, str) else str(v), -1)
             if v is not None else -1
             for v in values], np.int32)
        na = codes < 0
        data = np.where(na, 0, codes).astype(np.int32)
        ctype = T_CAT
    elif domain is not None:
        na = (values < 0) | ~np.isfinite(values.astype(np.float64))
        data = np.where(na, 0, values).astype(np.int32)
        ctype = T_CAT
    else:
        vals64 = values.astype(np.float64)
        na = ~np.isfinite(vals64)
        clean = np.where(na, 0.0, vals64)
        if facts is None:
            from h2o3_tpu.frame.partition import (local_numeric_facts,
                                                  merge_numeric_facts)
            facts = merge_numeric_facts([local_numeric_facts(values)])
        if facts["integral"]:
            data = clean.astype(block_int_dtype(facts["lo"], facts["hi"]))
        else:
            data = clean.astype(np.float32)
        ctype = T_NUM

    data = np.pad(data, (0, pad))
    na = np.pad(na, (0, pad), constant_values=True)
    if time and ctype == T_NUM:
        ctype = T_TIME
    col = Column(
        name=name, type=ctype,
        data=put_partitioned(data, sharding, (npad,)),
        na_mask=put_partitioned(na, sharding, (npad,)),
        nrows=nrows, domain=domain)
    # exact-f64 host semantics: retain THIS process's padded f64 slab;
    # Frame.from_numpy_partitioned then assembles the full host view
    # from every process's slabs in one batched device collective
    # (seed_partitioned_host_caches) while all processes are still at
    # the same program point — host_view() itself must stay
    # collective-free
    slab = data.astype(np.float64)
    slab[na] = np.nan
    if vals64 is not None and data.dtype == np.float32:
        slab[:local_n] = np.where(na[:local_n], np.nan, vals64)
    object.__setattr__(col, "_part_cache", slab)
    return col


# ---------------------------------------------------------------------------
# Block assembly — the chunk-parallel ingest building blocks.
#
# Reference: water/fvec/NewChunk.compress picks a codec per chunk; here a
# NumericBlock carries one window's narrowed values + NA mask + the
# integrality/range facts, and a BlockAccumulator (per column) ships each
# block to HBM as an async device_put, interns categorical domains
# globally, and reconciles the per-block narrowing into the final column
# dtype. The tokenize stage (pure, runs on worker threads) builds blocks;
# the in-order merge stage (caller thread) owns the accumulator, so the
# parallel and sequential ingest paths are bit-identical by construction.
# ---------------------------------------------------------------------------


def block_int_dtype(lo: float, hi: float):
    """Narrowest int dtype holding [lo, hi] (int8/int16/int32)."""
    if -128 <= lo and hi <= 127:
        return np.int8
    if -32768 <= lo and hi <= 32767:
        return np.int16
    return np.int32


@dataclasses.dataclass
class NumericBlock:
    """One window's worth of a numeric column, already narrowed."""
    clean: np.ndarray           # NA positions zero-filled
    na: np.ndarray              # bool mask, True = missing
    dtype: object               # narrow storage dtype for this block
    lo: float                   # block min of clean (0.0 when empty)
    hi: float                   # block max of clean (0.0 when empty)
    is_int: bool                # every value integral and |v| < 2**31


def narrow_numeric_block(values: np.ndarray,
                         na: Optional[np.ndarray] = None) -> NumericBlock:
    """Per-chunk codec selection (the NewChunk.compress role).

    With na=None the mask is derived from non-finite values (the CSV
    tokenizer path); Arrow callers pass validity-derived masks explicitly
    so integer buffers narrow without a float round trip.
    """
    if na is None:
        na = ~np.isfinite(values)
    else:
        na = np.asarray(na, bool)
    # NA-free blocks keep their buffer (zero-copy from Arrow readers:
    # device_put then ships the original buffer when the narrow dtype
    # already matches); blocks never get mutated downstream
    clean = np.where(na, 0, values) if na.any() else values
    # range check in float64: np.abs on int64 extremes would overflow
    # and sneak past the < 2**31 gate (f64 is a no-op copy=False view
    # on the CSV path, which is already float64)
    clean64 = clean.astype(np.float64, copy=False)
    is_int = bool(np.all(clean == np.round(clean)) and
                  np.all(np.abs(clean64) < 2**31))
    lo = float(clean64.min()) if clean.size else 0.0
    hi = float(clean64.max()) if clean.size else 0.0
    if is_int and clean.size:
        bd = block_int_dtype(lo, hi)
    elif is_int:
        bd = np.int8
    else:
        bd = np.float32
    return NumericBlock(clean=clean, na=na, dtype=bd,
                        lo=lo, hi=hi, is_int=is_int)


def block_values_f64(nb: NumericBlock) -> np.ndarray:
    """Reconstruct the block's float64 values with NaN at NAs (the
    categorical-promotion input)."""
    vals = nb.clean.astype(np.float64)
    if nb.na.any():
        vals[nb.na] = np.nan
    return vals


@_partial(jax.jit, static_argnames=("npad", "dtype", "sizes"))
def _assemble_col(parts, bit_parts, *, npad: int, dtype: str,
                  sizes: tuple):
    """Concatenate the per-window device blocks, upcast to the column's
    final dtype, pad, and build the NA mask from per-block packed bits
    (None = block had no NAs) — all on device. One program per
    (file-window-shape, dtype) signature; the persistent XLA cache
    amortizes it across runs."""
    from h2o3_tpu.parallel import mesh as mesh_mod
    segs = [p.astype(dtype) for p in parts]
    x = segs[0] if len(segs) == 1 else jnp.concatenate(segs)
    x = jnp.pad(x, (0, npad - x.shape[0]))
    x = jax.lax.with_sharding_constraint(x, mesh_mod.row_sharding())
    msegs = []
    for bits, sz in zip(bit_parts, sizes):
        if bits is None:
            msegs.append(jnp.zeros(sz, bool))
        else:
            idx = jnp.arange(sz, dtype=jnp.int32)
            b = bits[idx >> 3]
            msegs.append((
                (b >> (7 - (idx & 7)).astype(jnp.uint8)) & 1).astype(bool))
    m = msegs[0] if len(msegs) == 1 else jnp.concatenate(msegs)
    m = jnp.pad(m, (0, npad - m.shape[0]), constant_values=True)
    m = jax.lax.with_sharding_constraint(m, mesh_mod.row_sharding())
    return x, m


class BlockAccumulator:
    """Per-column accumulator: per-window NARROWED device blocks + the
    global categorical domain.

    Each window's slice ships immediately as an async device_put at the
    window-local narrow dtype (int8/int16 when the block's values fit —
    the NewChunk.compress codec role, applied per chunk like the
    reference), and NA masks ship as packed BITS only for blocks that
    have NAs. The wire through the tunneled chip is the ingest
    bottleneck (~15-20 MB/s measured), so bytes-on-wire is the budget:
    narrowing + bit-masks + transfer/tokenize overlap together turn
    sum(tokenize, transfer-at-4B/cell) into ~max(tokenize,
    transfer-at-1-2B/cell).

    Order contract: add_* calls MUST arrive in window order (the merge
    stage serializes them) — domain interning is append-only and block
    codes are final the moment they are pushed.
    """

    def __init__(self, name: str, time: bool = False):
        self.name = name
        self.time = time                     # finish() → T_TIME column
        self.parts: List[jax.Array] = []     # device blocks (async put)
        self.bit_parts: List[Optional[jax.Array]] = []
        self.sizes: List[int] = []
        self.levels: Dict[str, int] = {}     # global categorical domain
        self.order: List[str] = []
        self.is_cat = False

    def _push(self, clean: np.ndarray, na: np.ndarray, dtype):
        self.parts.append(jax.device_put(clean.astype(dtype, copy=False)))
        self.bit_parts.append(
            jax.device_put(np.packbits(na)) if na.any() else None)
        self.sizes.append(len(clean))

    def add_numeric_block(self, nb: NumericBlock):
        """Merge one pre-narrowed window block (tokenize-stage output)."""
        if self.is_cat:
            # numeric window inside a categorical column: values become
            # their string levels (the reference re-types the column)
            self.add_categorical(np.zeros(0, np.int32), [],
                                 raw_numeric=block_values_f64(nb))
            return
        # per-chunk integrality/range tracking for the FINAL dtype
        if not hasattr(self, "_all_int"):
            self._all_int, self._lo, self._hi = True, np.inf, -np.inf
        if self._all_int and nb.is_int:
            if nb.clean.size:
                self._lo = min(self._lo, nb.lo)
                self._hi = max(self._hi, nb.hi)
        else:
            self._all_int = False
        self._push(nb.clean, nb.na, nb.dtype)

    def add_numeric(self, arr: np.ndarray):
        self.add_numeric_block(narrow_numeric_block(arr))

    def add_categorical(self, codes: np.ndarray, domain: List[str],
                        raw_numeric: Optional[np.ndarray] = None):
        if not self.is_cat and self.parts:
            # column promoted to categorical mid-stream: earlier numeric
            # blocks are fetched back and re-expressed as levels (rare
            # type-drift path; one host round trip per prior window —
            # the reference re-parses the column in the same situation)
            old = list(zip(self.parts, self.bit_parts, self.sizes))
            self.parts, self.bit_parts, self.sizes = [], [], []
            self.is_cat = True
            for part, bits, sz in old:
                vals = np.asarray(part, np.float64)
                if bits is not None:
                    na_old = np.unpackbits(
                        np.asarray(bits), count=sz).astype(bool)
                    vals[na_old] = np.nan
                self.add_categorical(np.zeros(0, np.int32), [],
                                     raw_numeric=vals)
        self.is_cat = True
        if raw_numeric is not None:
            strs = np.array([None if np.isnan(v) else
                             (f"{v:g}") for v in raw_numeric], object)
            codes = np.empty(len(strs), np.int32)
            for i, s in enumerate(strs):
                if s is None:
                    codes[i] = -1
                else:
                    k = self.levels.get(s)
                    if k is None:
                        k = self.levels[s] = len(self.order)
                        self.order.append(s)
                    codes[i] = k
            remapped = codes
        else:
            lut = np.empty(max(len(domain), 1), np.int32)
            for j, lvl in enumerate(domain):
                k = self.levels.get(lvl)
                if k is None:
                    k = self.levels[lvl] = len(self.order)
                    self.order.append(lvl)
                lut[j] = k
            remapped = np.where(codes >= 0, lut[np.maximum(codes, 0)], -1)
        na = remapped < 0
        clean = np.where(na, 0, remapped)
        # interning is append-only, so block codes are final; narrow by
        # the block's max level index (upcast to int32 at assembly)
        self._push(clean, na,
                   block_int_dtype(0, float(clean.max(initial=0))))

    def finish(self, n: int, npad: int) -> Column:
        dtype = np.float32
        if self.is_cat:
            dtype = np.int32
        elif getattr(self, "_all_int", False):
            dtype = block_int_dtype(self._lo, self._hi)
        data, na = _assemble_col(tuple(self.parts), tuple(self.bit_parts),
                                 npad=npad, dtype=np.dtype(dtype).name,
                                 sizes=tuple(self.sizes))
        self.parts, self.bit_parts, self.sizes = [], [], []
        if self.is_cat:
            return Column(name=self.name, type=T_CAT, data=data,
                          na_mask=na, nrows=n, domain=list(self.order))
        return Column(name=self.name, type=T_TIME if self.time else T_NUM,
                      data=data, na_mask=na, nrows=n)
