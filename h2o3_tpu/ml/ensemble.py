"""StackedEnsemble — level-one frame from base-model CV predictions +
metalearner.

Reference: hex/ensemble/StackedEnsemble.java:29 — the level-one training
frame is assembled from each base model's cross-validation HOLDOUT
predictions (StackedEnsemble.java:205), so the metalearner never sees a
base model's in-bag fit; default metalearner is GLM
(hex/ensemble/Metalearners.java), any algo allowed.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu.parallel.mesh import fetch_replicated as _fetch_np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import get_builder
from h2o3_tpu.models.model import Model, ModelBuilder, ModelCategory


def _level_one_columns(model, frame: Optional[Frame]) -> Dict[str, np.ndarray]:
    """Base-model prediction columns: CV holdout (train time) or fresh
    predictions on ``frame`` (scoring time)."""
    cat = model.output["category"]
    mid = model.key
    if frame is None:
        h = model._cv_holdout
        if cat == ModelCategory.MULTINOMIAL:
            return {f"{mid}_p{k}": h[:, k] for k in range(h.shape[1])}
        return {mid: h}
    preds = model._score_raw(frame)
    if cat == ModelCategory.BINOMIAL:
        return {mid: np.asarray(preds["p1"])}
    if cat == ModelCategory.MULTINOMIAL:
        K = model.output["nclasses"]
        return {f"{mid}_p{k}": np.asarray(preds[f"p{k}"]) for k in range(K)}
    return {mid: np.asarray(preds["predict"])}


def _with_response(arrs: Dict[str, np.ndarray], yc, y: str, n: int) -> Frame:
    """Attach the response column preserving NAs (NA rows must NOT become
    class-0 labels — the metalearner excludes them like any builder)."""
    arrs = dict(arrs)
    if yc.is_categorical:
        codes = _fetch_np(yc.data)[:n].copy()
        na = _fetch_np(yc.na_mask)[:n]
        dom = yc.domain
        labels = np.asarray(dom, dtype=object)[np.maximum(codes, 0)]
        labels[na] = None
        arrs[y] = labels
        return Frame.from_numpy(arrs, categorical=[y], domains={y: dom})
    arrs[y] = yc.to_numpy()
    return Frame.from_numpy(arrs)


class StackedEnsembleModel(Model):
    algo = "stackedensemble"

    def __init__(self, params, output, base_models: List,
                 metalearner: Model):
        super().__init__(params, output)
        self.base_models = base_models
        self.metalearner = metalearner

    def _level_one(self, frame: Frame) -> Frame:
        cols: Dict[str, np.ndarray] = {}
        for m in self.base_models:
            cols.update(_level_one_columns(m, frame))
        return Frame.from_numpy(cols)

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        return self.metalearner._score_raw(self._level_one(frame))

    def model_performance(self, frame: Frame):
        l1f = self._level_one(frame)
        y = self.output["response"]
        arrs = {n: l1f.col(n).to_numpy() for n in l1f.names}
        l1y = _with_response(arrs, frame.col(y), y, frame.nrows)
        return self.metalearner.model_performance(l1y)


class StackedEnsembleEstimator(ModelBuilder):
    """h2o-py H2OStackedEnsembleEstimator-compatible surface."""

    algo = "stackedensemble"

    DEFAULTS = dict(
        base_models=(), metalearner_algorithm="AUTO",
        metalearner_params=None, metalearner_nfolds=0, seed=-1,
        ignored_columns=None,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown StackedEnsemble params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        from h2o3_tpu.core.kv import DKV
        base = [m if isinstance(m, Model) else DKV.get(m)
                for m in p["base_models"]]
        if len(base) < 2:
            raise ValueError("StackedEnsemble needs >= 2 base models")
        for m in base:
            if getattr(m, "_cv_holdout", None) is None:
                raise ValueError(
                    f"base model {m.key} lacks CV holdout predictions; "
                    "train base models with nfolds >= 2")
        cat = base[0].output["category"]

        # level-one training frame from CV holdouts (StackedEnsemble.java:205)
        cols: Dict[str, np.ndarray] = {}
        for m in base:
            cols.update(_level_one_columns(m, None))
        l1f = _with_response(cols, frame.col(y), y, frame.nrows)

        meta_algo = str(p["metalearner_algorithm"]).lower()
        meta_params = dict(p["metalearner_params"] or {})
        if meta_algo == "auto":
            meta_algo = "glm"
            # AUTO default: non-negative GLM weights (Metalearners.java)
            meta_params.setdefault("lambda_", 0.0)
        if int(p["metalearner_nfolds"]):
            meta_params["nfolds"] = int(p["metalearner_nfolds"])
        builder = get_builder(meta_algo)(**meta_params)
        job.update(0.5, "training metalearner")
        meta = builder.train(l1f, y=y)

        output = {"category": cat, "response": y,
                  "names": [m.key for m in base],
                  "nclasses": base[0].output.get("nclasses", 1),
                  "domain": base[0].output.get("domain"),
                  "metalearner": meta.key,
                  "base_models": [m.key for m in base]}
        model = StackedEnsembleModel(p, output, base, meta)
        model.training_metrics = meta.training_metrics
        model.cross_validation_metrics = meta.cross_validation_metrics
        if validation_frame is not None:
            model.validation_metrics = model.model_performance(validation_frame)
        return model
