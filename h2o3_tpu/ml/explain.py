"""Model explanation tools — partial dependence + permutation importance.

Reference: water/api/PartialDependenceHandler.java (h2o.partial_plot:
per-feature grid sweep, mean/stddev of predictions with the column
pinned) and hex/PermutationVarImp.java (metric drop after shuffling one
column at a time).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.explain")


def _pred_column(model, frame: Frame) -> np.ndarray:
    """The prediction the PDP averages: P(class 1) for binomial, the
    numeric prediction otherwise (PartialDependenceHandler contract)."""
    out = model._score_raw(frame)
    if "p1" in out:
        return np.asarray(out["p1"], dtype=np.float64)
    return np.asarray(out["predict"], dtype=np.float64)


def partial_dependence(model, frame: Frame, cols: Sequence[str],
                       nbins: int = 20) -> Dict[str, dict]:
    """Per-feature PDP tables {col: {values, mean_response, std_response,
    std_error}} (PartialDependenceHandler.makePDP)."""
    from h2o3_tpu.models.generic import _frame_raw_columns
    raw = _frame_raw_columns(frame, frame.names)
    cats = [n for n in frame.names if frame.col(n).is_categorical]
    n = frame.nrows
    out: Dict[str, dict] = {}
    for col in cols:
        c = frame.col(col)
        if c.is_categorical:
            grid_vals: List = list(c.domain or [])
        else:
            v = c.to_numpy()
            v = v[np.isfinite(v)]
            qs = np.linspace(0.05, 0.95, min(nbins, max(len(np.unique(v)), 2)))
            grid_vals = list(np.unique(np.quantile(v, qs)))
        means, stds, ses = [], [], []
        for gv in grid_vals:
            cols2 = dict(raw)
            cols2[col] = np.full(n, gv, dtype=object if c.is_categorical
                                 else np.float64)
            fr2 = Frame.from_numpy(cols2, categorical=cats)
            p = _pred_column(model, fr2)[:n]
            means.append(float(np.nanmean(p)))
            stds.append(float(np.nanstd(p)))
            ses.append(float(np.nanstd(p) / np.sqrt(max(n, 1))))
        out[col] = {"values": grid_vals, "mean_response": means,
                    "std_response": stds, "std_error_mean_response": ses}
    return out


def permutation_varimp(model, frame: Frame, metric: str = "auto",
                       n_repeats: int = 1, seed: int = 0) -> List[tuple]:
    """Permutation importance rows (variable, relative, scaled, pct) —
    hex/PermutationVarImp semantics: metric degradation when one
    feature's values are shuffled."""
    from h2o3_tpu.models.generic import _frame_raw_columns
    features = model.output.get("names") or []
    raw = _frame_raw_columns(frame, frame.names)
    cats = [n for n in frame.names if frame.col(n).is_categorical]
    n = frame.nrows
    rng = np.random.RandomState(seed)

    def _metric_of(fr) -> float:
        mm_ = model.model_performance(fr)
        d = mm_.to_dict() if hasattr(mm_, "to_dict") else dict(mm_)
        if metric != "auto":
            return float(d[metric])
        for k in ("logloss", "mean_residual_deviance", "MSE"):
            if d.get(k) is not None:
                return float(d[k])
        raise ValueError("no usable metric")

    base = _metric_of(frame)
    rows = []
    for f in features:
        deltas = []
        for _ in range(max(n_repeats, 1)):
            cols2 = dict(raw)
            perm = rng.permutation(n)
            cols2[f] = np.asarray(raw[f])[:n][perm]
            fr2 = Frame.from_numpy(cols2, categorical=cats)
            deltas.append(_metric_of(fr2) - base)
        rows.append((f, float(np.mean(deltas))))
    vals = np.asarray([max(v, 0.0) for _, v in rows])
    vmax, vsum = max(vals.max(), 1e-12), max(vals.sum(), 1e-12)
    table = [(f, float(v), float(v / vmax), float(v / vsum))
             for (f, _), v in zip(rows, vals)]
    table.sort(key=lambda r: -r[1])
    return table
