"""Probability calibration for binomial tree models.

Reference: hex/tree/SharedTree calibrate_model/calibration_frame/
calibration_method — after training, fit Platt scaling (a 1-feature
logistic regression on the raw scores, CalibrationHelper) or isotonic
regression mapping raw probabilities to calibrated ones; scoring then
appends cal_p0/cal_p1 columns.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.calibration")


def fit_platt(p_raw: np.ndarray, y01: np.ndarray,
              iters: int = 50) -> Tuple[float, float]:
    """Newton logistic fit of y on logit(p): returns (a, b) with
    cal_p = sigmoid(a * logit(p) + b)."""
    z = np.log(np.clip(p_raw, 1e-7, 1 - 1e-7)
               / np.clip(1 - p_raw, 1e-7, 1 - 1e-7))
    a, b = 1.0, 0.0
    for _ in range(iters):
        eta = a * z + b
        mu = 1.0 / (1.0 + np.exp(-np.clip(eta, -30, 30)))
        wv = np.maximum(mu * (1 - mu), 1e-9)
        g = np.array([np.sum((mu - y01) * z), np.sum(mu - y01)])
        H = np.array([[np.sum(wv * z * z), np.sum(wv * z)],
                      [np.sum(wv * z), np.sum(wv)]])
        try:
            step = np.linalg.solve(H + 1e-9 * np.eye(2), g)
        except np.linalg.LinAlgError:
            break
        a, b = a - step[0], b - step[1]
        if np.abs(step).max() < 1e-10:
            break
    return float(a), float(b)


def fit_isotonic(p_raw: np.ndarray, y01: np.ndarray):
    """Pool-adjacent-violators p→E[y] map; returns (x, y) step points."""
    order = np.argsort(p_raw, kind="stable")
    x = p_raw[order].astype(np.float64)
    y = y01[order].astype(np.float64)
    # classic PAV merge (hex/isotonic semantics)
    v, ww, xx = [], [], []
    for i in range(len(y)):
        v.append(y[i]); ww.append(1.0); xx.append(x[i])
        while len(v) > 1 and v[-2] > v[-1]:
            m = (v[-2] * ww[-2] + v[-1] * ww[-1]) / (ww[-2] + ww[-1])
            wnew = ww[-2] + ww[-1]
            xnew = xx[-1]
            v.pop(); ww.pop(); xx.pop()
            v[-1], ww[-1], xx[-1] = m, wnew, xnew
    return np.asarray(xx), np.asarray(v)


class Calibrator:
    """Fitted calibration map attachable to a binomial model."""

    def __init__(self, method: str, params):
        self.method = method
        self.params = params

    def apply(self, p1: np.ndarray) -> np.ndarray:
        if self.method == "plattscaling":
            a, b = self.params
            z = np.log(np.clip(p1, 1e-7, 1 - 1e-7)
                       / np.clip(1 - p1, 1e-7, 1 - 1e-7))
            return 1.0 / (1.0 + np.exp(-np.clip(a * z + b, -30, 30)))
        xs, ys = self.params
        if len(xs) == 0:
            return p1
        return np.interp(np.clip(p1, xs[0], xs[-1]), xs, ys)


def maybe_calibrate(model, params: dict, category: str) -> None:
    """Shared GBM/DRF post-train hook: validate + fit the calibrator
    when calibrate_model is set (CalibrationHelper.initCalibration
    validation semantics)."""
    if not params.get("calibrate_model"):
        return
    if category != "Binomial":
        raise ValueError("calibrate_model is only supported for binomial "
                         f"models (got {category})")
    cf = params.get("calibration_frame")
    if cf is None:
        raise ValueError("calibrate_model requires calibration_frame")
    from h2o3_tpu.frame.frame import Frame
    if not isinstance(cf, Frame):
        from h2o3_tpu.core.kv import DKV
        key = str(cf)
        cf = DKV.get(key)
        if not isinstance(cf, Frame):
            raise ValueError(f"calibration_frame '{key}' not found")
    calibrate_model(model, cf,
                    method=params.get("calibration_method", "PlattScaling"))


def calibrate_model(model, calibration_frame, method: str = "PlattScaling"):
    """Fit + attach a calibrator (CalibrationHelper.buildCalibrationModel);
    model.predict gains cal_p0/cal_p1 columns afterwards."""
    from h2o3_tpu.models.model import adapt_domain
    y = model.output["response"]
    p1 = np.asarray(model._score_raw(calibration_frame)["p1"],
                    dtype=np.float64)
    yv = adapt_domain(calibration_frame.col(y), model.output["domain"])
    ok = yv >= 0
    m = str(method).lower().replace("_", "")
    if m == "plattscaling":
        cal = Calibrator(m, fit_platt(p1[ok], yv[ok].astype(float)))
    elif m in ("isotonicregression", "isotonic"):
        cal = Calibrator("isotonic", fit_isotonic(p1[ok],
                                                  yv[ok].astype(float)))
    else:
        raise ValueError(f"unknown calibration_method '{method}'")
    model.calibrator = cal
    return cal
