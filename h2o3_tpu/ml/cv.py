"""n-fold cross-validation — the computeCrossValidation path.

Reference: hex/ModelBuilder.java:603 — build fold assignment, train
nfolds models on (N - fold) rows each (CVModelBuilder sweep at :819),
score each holdout, merge holdout predictions into one frame, compute CV
metrics from it, then train the final model on all data. Same here;
fold models run sequentially (parallel fold training over spare mesh
slices is the reference's parallelism #5, SURVEY §2.4).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from h2o3_tpu.parallel.mesh import fetch_replicated as _fetch_np
from h2o3_tpu.parallel.mesh import padded_rows as _pad_rows
from h2o3_tpu.parallel import scheduler as _scheduler

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import metrics as mm
from h2o3_tpu.models.model import ModelCategory, adapt_domain, infer_category


def fold_assignment(n: int, nfolds: int, scheme: str = "modulo",
                    seed: int = 0xF01D, y: Optional[np.ndarray] = None) -> np.ndarray:
    """Fold ids per row (reference FoldAssignment / AstKFold schemes:
    AUTO→Random, Modulo, Stratified)."""
    if scheme in ("modulo",):
        return (np.arange(n) % nfolds).astype(np.int32)
    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    if scheme == "stratified" and y is not None:
        folds = np.zeros(n, np.int32)
        for cls in np.unique(y):
            idx = np.where(y == cls)[0]
            rng.shuffle(idx)
            folds[idx] = np.arange(len(idx)) % nfolds
        return folds
    return rng.randint(0, nfolds, size=n).astype(np.int32)


def subset_frame(frame: Frame, keep: np.ndarray,
                 pad_to: Optional[int] = None) -> Frame:
    """Host-side row subset (reference uses fold-weight columns instead;
    a weights-based device path is the planned optimization). ``pad_to``
    pads the subset to a caller-chosen device shape — CV passes the
    parent frame's padded size so every fold (and the final full-data
    fit) compiles ONE program instead of one per fold size."""
    arrays, domains, cats = {}, {}, []
    for name in frame.names:
        c = frame.col(name)
        if c.type == "string":
            arrays[name] = c.strings[:frame.nrows][keep]
            continue
        v = _fetch_np(c.data)[: frame.nrows][keep]
        if c.is_categorical:
            v = v.astype(np.int32)
            v[_fetch_np(c.na_mask)[: frame.nrows][keep]] = -1
            domains[name] = c.domain
            cats.append(name)
            arrays[name] = v
        else:
            vv = v.astype(np.float64)
            vv[_fetch_np(c.na_mask)[: frame.nrows][keep]] = np.nan
            arrays[name] = vv
    return Frame.from_numpy(arrays, categorical=cats, domains=domains,
                            pad_to=pad_to)


def _glm_path_holdout_deviance(m, te: Frame, y: str, p: dict) -> np.ndarray:
    """Per-lambda deviance of a GLM fold model's coefficient path on
    its holdout frame — the statistic the reference's lambda-search CV
    minimizes (GLM.java xval deviance). Honors the user weights column
    and the offset column, like the CV metrics themselves."""
    import jax.numpy as jnp
    from h2o3_tpu.models.model import ModelCategory, adapt_domain
    X1 = m._design(te)                         # [n_pad, P+1]
    path = m._coef_path                        # [L, P+1]
    n = te.nrows
    w = np.asarray(te.valid_weights())[:n]
    if p.get("weights_column") and p["weights_column"] in te:
        wraw = te.col(p["weights_column"]).to_numpy()
        w = w * np.nan_to_num(wraw).astype(np.float32)
    yc = te.col(y)
    if m.output["category"] == ModelCategory.BINOMIAL:
        yv = adapt_domain(yc, m.output["domain"])
        w = w * (yv >= 0)
        yv = np.maximum(yv, 0).astype(np.float32)
    else:
        yraw = yc.to_numpy()
        w = w * (~np.isnan(yraw))
        yv = np.nan_to_num(yraw).astype(np.float32)
    yv = np.pad(yv, (0, X1.shape[0] - n))
    w = np.pad(w, (0, X1.shape[0] - n))
    etas = X1 @ jnp.asarray(path.T, jnp.float32)              # [n, L]
    off = m._frame_offset(te)
    if off is not None:
        etas = etas + off[:, None]
    fam = m.family
    mus = np.asarray(fam.linkinv(etas))
    devs = np.asarray(fam.deviance(jnp.asarray(yv)[:, None],
                                   jnp.asarray(mus)))
    return (w[:, None] * devs).sum(axis=0)


def train_with_cv(builder, frame: Frame, x: Sequence[str], y: str,
                  nfolds: int, job, validation_frame: Optional[Frame] = None):
    """Train nfolds+1 models; attach CV metrics to the final model.
    A validation_frame flows to the final (main) model only, like the
    reference (ModelBuilder.java cv_main model keeps _valid)."""
    p = dict(builder.params)
    scheme = str(p.get("fold_assignment", "auto") or "auto").lower()
    if scheme == "auto":
        # AUTO resolves to seeded Random (ModelBuilder.cv_AssignFold:
        # `case AUTO: case Random:` share the kfoldColumn branch) — a
        # modulo default made different seeds produce IDENTICAL CV
        # models (pyunit_glm_seed's seed-difference assertion)
        scheme = "random"
    raw_seed = p.get("seed")
    if raw_seed is None or int(raw_seed) < 0:
        # getOrMakeRealSeed: unset seed draws a REAL random one, so two
        # unseeded Random-fold runs genuinely differ (pyunit_cv_carsRF)
        seed = int(np.random.SeedSequence().entropy % (2 ** 31))
    else:
        seed = int(raw_seed)
    category = infer_category(frame, y)

    if p.get("fold_column"):
        folds = _fetch_np(frame.col(p["fold_column"]).data)[: frame.nrows].astype(np.int32)
        nfolds = int(folds.max()) + 1
    else:
        yv = None
        if scheme == "stratified":
            yv = _fetch_np(frame.col(y).data)[: frame.nrows]
        folds = fold_assignment(frame.nrows, nfolds, scheme, seed, yv)

    sub_params = {**p, "nfolds": 0, "fold_column": None}
    cap_total = float(p.get("max_runtime_secs") or 0.0)
    if cap_total > 0:
        # the cap covers the WHOLE train incl. CV (ModelBuilder
        # cv_computeAndSetOptimalParameters role): the MAIN model keeps
        # half the budget, folds share the other half — an even
        # (nfolds+1)-way split strangled the main model whenever the
        # masked-weight fold fits were cheap
        sub_params["max_runtime_secs"] = \
            cap_total / 2.0 / max(nfolds, 1)
    job._work = nfolds + 1.0  # nfolds CV fits + the final model

    if y is None:
        # unsupervised CV (KMeans nfolds, hex/ModelBuilder unsupervised
        # path): train per-fold models + the final model; CV metrics are
        # the final model's metrics minus centroid_stats (the reference
        # serves cv metrics with centroid_stats == null —
        # pyunit_kmeans_cv contract)
        cv_models = []
        for f in range(nfolds):
            # honor the computed fold assignment (fold_column / scheme /
            # seed) — the unsupervised branch must not silently fall back
            # to a modulo split
            mask_tr = folds != f
            tr = subset_frame(frame, mask_tr, pad_to=frame.nrows_padded)
            m = builder.__class__(**sub_params)._fit(tr, list(x), None, job)
            cv_models.append(m)
        final = builder.__class__(**sub_params)._fit(
            frame, list(x), None, job, validation_frame=validation_frame)
        import copy
        cvm = copy.copy(final.training_metrics)
        if cvm is not None and hasattr(cvm, "extra"):
            cvm.extra = dict(cvm.extra)
            cvm.extra["centroid_stats"] = None
        final.cross_validation_metrics = cvm
        from h2o3_tpu.core.kv import DKV
        cv_keys = []
        for i, m in enumerate(cv_models):
            new_key = f"{final.key}_cv_{i + 1}"
            DKV.remove(m.key)
            m.key = new_key
            DKV.put(new_key, m)
            cv_keys.append(new_key)
        final.output["cv_model_keys"] = cv_keys
        final.output["nfolds"] = nfolds
        final._cv_models = cv_models
        return final

    n = frame.nrows
    cv_models = []
    if category == ModelCategory.MULTINOMIAL:
        K = frame.col(y).cardinality
        holdout = np.zeros((n, K), np.float32)
    else:
        holdout = np.zeros((n,), np.float32)

    keep_preds = bool(p.get("keep_cross_validation_predictions"))
    cv_pred_keys = []
    fold_metric_dicts = []
    path_devs = []      # per-fold per-lambda holdout deviance (GLM search)
    dev_scores = []     # (holdout idx, device score) — light-mode async sweep

    # CV fast path (tree builders): fold models train on the PARENT
    # frame with held-out rows weight-masked and the main model's bin
    # edges shared, so the whole sweep reuses ONE compiled program and
    # never rebuilds frames — leave-one-out CV (nfolds == nrows,
    # pyunit_cv_cars_gbm boundary case) costs one dispatch per fold
    # instead of a frame rebuild + bin re-sketch per fold.
    fast = bool(getattr(builder, "cv_fold_masking", False)) \
        and not p.get("checkpoint")
    if fast and builder.algo == "glm" and (
            p.get("lambda_search") or
            (p.get("lambda_") not in (None, 0, 0.0))):
        # penalized GLM folds must standardize per fold (the penalty
        # couples to the sigma scaling), so the shared-design fast path
        # only covers unpenalized fits; regularized CV keeps the
        # subset-frame path with per-fold DataInfo like the reference
        fast = False
    final = None
    shared_bm = None
    main_params = dict(sub_params)
    if cap_total > 0:
        main_params["max_runtime_secs"] = cap_total / 2.0
    if fast:
        # main model FIRST: folds reuse its full-data binning (GLM has
        # no binned matrix — folds share the design implicitly, since
        # the masked rows ride the same parent frame)
        final = builder.__class__(**main_params)._fit(
            frame, list(x), y, job, validation_frame=validation_frame)
        shared_bm = getattr(final, "bm", None)

    # near-leave-one-out CV (the nfolds ≈ nrows boundary case,
    # pyunit_cv_cars_gbm) drops per-fold frills whose device syncs
    # dominate: fold training metrics, varimp, and per-fold holdout
    # metric dicts — the CV metric over the merged holdout (below) is
    # the contract that matters. Ordinary nfolds keep full fidelity.
    light = fast and nfolds >= max(100, 0.5 * frame.nrows)
    if light:
        from h2o3_tpu.utils.log import get_logger
        get_logger("h2o3_tpu.cv").info(
            "near-LOO CV (nfolds=%d on %d rows): skipping per-fold "
            "metric/varimp frills", nfolds, frame.nrows)

    # GLM lambda search under CV: train the MAIN model first to fix one
    # full-frame lambda path, have every fold walk that SAME path (so
    # per-lambda holdout deviances align index-wise), then re-fit the
    # main model at the CV-selected lambda (GLM.java xval-deviance
    # lambda selection).
    shared_lambda_path = None
    glm_search = (getattr(builder, "algo", "") == "glm"
                  and p.get("lambda_search") and not fast)
    if glm_search:
        probe = builder.__class__(**sub_params)._fit(frame, list(x), y, job)
        shared_lambda_path = getattr(probe, "_lambda_path_vals", None)
        from h2o3_tpu.core.kv import DKV as _DKV
        _DKV.remove(probe.key)
        del probe

    # ---- cluster-scheduled fold models (parallel/scheduler.py) -------
    # the subset-frame fold path is embarrassingly parallel: each fold
    # trains on its own rebuilt frame with no shared device state, so on
    # a multi-host cloud the folds fan out as work items (local mesh +
    # host frame copies) and come back as device-independent model bytes
    # every process installs identically. The fast path (shared binning
    # + fold masking on the parent frame) and GLM lambda-search CV keep
    # their single-program sweeps — scheduling would break the sharing
    # that makes them fast.
    sched_folds = None
    if (_scheduler.active() and not fast and not glm_search
            and not p.get("checkpoint") and nfolds >= 2):
        max_fold = int(np.max(np.bincount(folds, minlength=nfolds)))

        def _cv_execute(f):
            from h2o3_tpu.parallel import mesh as mesh_mod
            with mesh_mod.local_mesh_scope():
                lf = frame.local_copy()
                mask_tr = folds != f
                tr = subset_frame(lf, mask_tr, pad_to=lf.nrows_padded)
                te = subset_frame(lf, ~mask_tr,
                                  pad_to=_pad_rows(max_fold, block=8))
                sub = builder.__class__(**sub_params)
                m = sub._fit(tr, list(x), y, job)
                preds = {k: np.asarray(v)
                         for k, v in m._score_raw(te).items()}
                try:
                    fm = m.model_performance(te)
                    fmd = fm.to_dict() if hasattr(fm, "to_dict") else {}
                except Exception:    # noqa: BLE001 - summary-only data
                    fmd = {}
                return _scheduler.lower_to_bytes(
                    (_scheduler.detach_model(m), preds, fmd))

        res = _scheduler.run(f"cv:{builder.algo}:{nfolds}f", nfolds,
                             _cv_execute, job=job)
        sched_folds = {}
        for f in sorted(res):
            rec = res[f]
            if not rec["ok"]:
                # the owning host's training error — sequential CV
                # would have raised the same error out of its fold loop
                raise RuntimeError(rec["error"])
            m, preds_f, fmd = _scheduler.from_bytes(rec["data"])
            sched_folds[f] = (_scheduler.install_model(m), preds_f, fmd)

    for f in range(nfolds):
        mask_tr = folds != f
        idx = np.where(~mask_tr)[0]
        if fast:
            sub = builder.__class__(**sub_params)
            sub._cv_fold_mask = mask_tr
            sub._cv_shared_bm = shared_bm
            sub._cv_light = light
            m = sub._fit(frame, list(x), y, job)
            if light and not keep_preds and hasattr(m, "_score_dev"):
                # near-LOO async pipeline: keep every fold's holdout
                # score ON DEVICE and fetch the whole sweep in one
                # batched transfer after the loop — the per-fold
                # blocking fetch was a ~100ms tunnel round trip × nfolds
                # (pyunit_cv_carsRF's 583s). Periodic block bounds the
                # number of in-flight fold forests in HBM.
                dev_scores.append((idx, m._score_dev(frame)))
                if len(dev_scores) % 64 == 0:
                    dev_scores[-1][1].block_until_ready()
                from h2o3_tpu.core.kv import DKV as _DKV
                _DKV.remove(m.key)
                del m
                fold_metric_dicts.append({})
                continue
            full_preds = m._score_raw(frame)
            preds = {k: np.asarray(v)[idx] for k, v in full_preds.items()}
            if light:
                # near-LOO: fold models are NOT retained — hundreds of
                # padded complete-tree forests (~100MB each on device)
                # exhaust HBM long before the sweep ends; the merged
                # holdout predictions (the CV metric contract) are
                # already extracted above
                from h2o3_tpu.core.kv import DKV as _DKV
                _DKV.remove(m.key)
                del m
                fold_metric_dicts.append({})
            else:
                cv_models.append(m)
                hold_w = np.zeros(frame.nrows_padded, np.float32)
                hold_w[idx] = 1.0
                try:
                    fm = m.model_performance(frame, mask_weights=hold_w)
                    fold_metric_dicts.append(
                        fm.to_dict() if hasattr(fm, "to_dict") else {})
                except Exception:
                    fold_metric_dicts.append({})
        elif sched_folds is not None:
            m, preds, fmd = sched_folds.pop(f)
            cv_models.append(m)
            fold_metric_dicts.append(fmd)
        else:
            tr = subset_frame(frame, mask_tr, pad_to=frame.nrows_padded)
            # holdouts share one padded shape too (all ~n/nfolds rows;
            # max fold size keeps one scoring program across folds)
            te = subset_frame(frame, ~mask_tr,
                              pad_to=_pad_rows(int(np.max(
                                  np.bincount(folds, minlength=nfolds))),
                                  block=8))
            sub = builder.__class__(**sub_params)
            if shared_lambda_path:
                sub.params["_lambda_path_override"] = shared_lambda_path
            m = sub._fit(tr, list(x), y, job)
            cv_models.append(m)
            if shared_lambda_path and \
                    getattr(m, "_coef_path", None) is not None:
                path_devs.append(_glm_path_holdout_deviance(m, te, y, p))
            preds = m._score_raw(te)
            # per-fold holdout metrics feed
            # cross_validation_metrics_summary (reference cvModelBuilder
            # per-fold _validation metrics)
            try:
                fm = m.model_performance(te)
                fold_metric_dicts.append(fm.to_dict()
                                         if hasattr(fm, "to_dict") else {})
            except Exception:
                fold_metric_dicts.append({})
        if category == ModelCategory.BINOMIAL:
            holdout[idx] = preds["p1"]
        elif category == ModelCategory.MULTINOMIAL:
            for k in range(K):
                holdout[idx, k] = preds[f"p{k}"]
        else:
            holdout[idx] = preds["predict"]
        if keep_preds:
            # per-fold holdout prediction frame: full nrows, zeros off-fold
            # (reference keep_cross_validation_predictions contract)
            cols = {}
            for name, arr in preds.items():
                a = np.asarray(arr, np.float64)
                if a.dtype.kind not in "fiu":
                    continue
                fullcol = np.zeros(n, np.float64)
                fullcol[idx] = a[: len(idx)]
                cols[name] = fullcol
            pf = Frame.from_numpy(cols)
            cv_pred_keys.append(pf.key)

    if dev_scores:
        # ONE batched device→host transfer merges the whole light sweep
        fetched = _fetch_np([a for _, a in dev_scores])
        for (idx2, _), arr in zip(dev_scores, fetched):
            holdout[idx2] = np.asarray(arr)[idx2]
        dev_scores.clear()

    # final model on all data (ModelBuilder.java "main model") — the
    # fast path trained it up front to share its binning with the folds
    if final is None:
        fb = builder.__class__(**main_params)
        if path_devs:
            # GLM lambda search under CV selects the lambda minimizing
            # the SUMMED holdout deviance over the folds' SHARED path
            # (the reference's xval-deviance selection) — this is why
            # two different CV seeds legitimately yield different final
            # coefficients (pyunit_glm_seed h2oglm_3 != h2oglm_4)
            tot = np.sum(np.stack(path_devs), axis=0)
            lam_best = shared_lambda_path[int(np.argmin(tot))]
            fb.params["_lambda_path_override"] = shared_lambda_path
            fb.params["_cv_selected_lambda"] = float(lam_best)
        final = fb._fit(
            frame, list(x), y, job, validation_frame=validation_frame)

    # CV metrics: NA-response rows excluded, user weights applied — same
    # weighting contract as training metrics
    yc = frame.col(y)
    wv = np.ones(n, np.float32)
    if p.get("weights_column") and p["weights_column"] in frame:
        wraw = frame.col(p["weights_column"]).to_numpy()
        wv = np.nan_to_num(wraw).astype(np.float32)
    if category in (ModelCategory.BINOMIAL, ModelCategory.MULTINOMIAL):
        yv = adapt_domain(yc, yc.domain)
        wv = wv * (yv >= 0)
        yv = np.maximum(yv, 0)
        if category == ModelCategory.BINOMIAL:
            final.cross_validation_metrics = mm.binomial_metrics(
                holdout, yv.astype(np.float32), wv)
        else:
            final.cross_validation_metrics = mm.multinomial_metrics(
                holdout, yv, wv, domain=yc.domain)
    else:
        yraw = yc.to_numpy()
        wv = wv * (~np.isnan(yraw)).astype(np.float32)
        yv = np.nan_to_num(yraw).astype(np.float32)
        final.cross_validation_metrics = mm.regression_metrics(holdout, yv, wv)
    # combined holdout-prediction frame + fold-assignment frame
    # (reference cross_validation_holdout_predictions_frame_id /
    # cross_validation_fold_assignment_frame_id outputs)
    if keep_preds:
        if category == ModelCategory.MULTINOMIAL:
            hcols = {f"p{k}": holdout[:, k].astype(np.float64)
                     for k in range(holdout.shape[1])}
            hcols = {"predict": holdout.argmax(axis=1).astype(np.float64),
                     **hcols}
        elif category == ModelCategory.BINOMIAL:
            t = final.output.get("default_threshold", 0.5)
            hcols = {"predict": (holdout >= t).astype(np.float64),
                     "p0": (1.0 - holdout).astype(np.float64),
                     "p1": holdout.astype(np.float64)}
        else:
            hcols = {"predict": holdout.astype(np.float64)}
        hf = Frame.from_numpy(hcols)
        final.output["cv_holdout_frame_key"] = hf.key
    else:
        final.output["cv_holdout_frame_key"] = None
    if p.get("keep_cross_validation_fold_assignment"):
        faf = Frame.from_numpy({"fold_assignment":
                                folds.astype(np.float64)})
        final.output["cv_fold_assignment_key"] = faf.key
    else:
        final.output["cv_fold_assignment_key"] = None
    final.output["cv_holdout_predictions"] = None
    final.output["cv_predictions_keys"] = cv_pred_keys or None
    final.output["nfolds"] = nfolds
    # expose CV models to clients like the reference does: keys named
    # {main}_cv_{i}, listed under output.cross_validation_models
    # (hex/ModelBuilder.java:819 cv-model naming)
    from h2o3_tpu.core.kv import DKV
    cv_keys = []
    for i, m in enumerate(cv_models):
        new_key = f"{final.key}_cv_{i + 1}"
        DKV.remove(m.key)
        m.key = new_key
        DKV.put(new_key, m)
        cv_keys.append(new_key)
    final.output["cv_model_keys"] = cv_keys
    # mean/sd/per-fold summary rows (client
    # cross_validation_metrics_summary)
    keys_union = sorted({k for d in fold_metric_dicts for k, v in d.items()
                         if isinstance(v, (int, float))})
    summary_rows = []
    for kname in keys_union:
        # keep one slot per fold (None where the metric is absent or the
        # fold's scoring failed): twodim transposes these rows against a
        # fixed 2+nfolds column set, so a short row 500s GET /3/Models
        per_fold = [float(d[kname])
                    if isinstance(d.get(kname), (int, float)) else None
                    for d in fold_metric_dicts]
        vals = [v for v in per_fold if v is not None]
        if not vals:
            continue
        summary_rows.append(
            [kname, float(np.mean(vals)), float(np.std(vals))] + per_fold)
    final.output["cv_summary_rows"] = summary_rows
    final.output["cv_summary_nfolds"] = nfolds
    final._cv_holdout = holdout
    final._cv_models = cv_models
    final._cv_folds = folds
    return final
