"""Segment models — bulk-train one model per data segment.

Reference: hex/segments (SegmentModels.java, SegmentModelsBuilder):
h2o-py's ``train_segments`` splits the frame by the distinct values of
``segment_columns``, trains the same algorithm/params on every segment,
and collects per-segment model keys + status into a results frame.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu.core.kv import DKV, make_key
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.segments")


class SegmentModels:
    """Per-segment training results (hex/segments/SegmentModels.java)."""

    def __init__(self, key: str, segment_columns: List[str],
                 results: List[dict]):
        self.key = key
        self.segment_columns = segment_columns
        self.results = results
        DKV.put(key, self)

    def as_frame(self) -> Frame:
        cols: Dict[str, np.ndarray] = {}
        for sc in self.segment_columns:
            cols[sc] = np.asarray([r["segment"][sc] for r in self.results],
                                  dtype=object)
        cols["model"] = np.asarray(
            [r.get("model_key") or "" for r in self.results], dtype=object)
        cols["status"] = np.asarray([r["status"] for r in self.results],
                                    dtype=object)
        cols["errors"] = np.asarray([r.get("error") or "" for r in self.results],
                                    dtype=object)
        return Frame.from_numpy(cols, categorical=list(cols.keys()))


def train_segments(builder_cls, params: dict, frame: Frame,
                   segment_columns: Sequence[str], y: Optional[str] = None,
                   x: Optional[Sequence[str]] = None,
                   parallelism: int = 1) -> SegmentModels:
    """The SegmentModelsBuilder.buildSegmentModels flow: enumerate
    distinct segment tuples, subset rows, train one model each.
    Failures are recorded per segment, not fatal (reference semantics)."""
    from h2o3_tpu.models.generic import _frame_raw_columns

    seg_cols = list(segment_columns)
    raw = _frame_raw_columns(frame, frame.names)
    n = frame.nrows
    seg_vals = np.empty((n, len(seg_cols)), dtype=object)
    for j, sc in enumerate(seg_cols):
        seg_vals[:, j] = raw[sc][:n]
    keys = [tuple(seg_vals[i]) for i in range(n)]
    uniq = sorted(set(keys), key=lambda t: tuple(str(v) for v in t))
    cats = [nm for nm in frame.names if frame.col(nm).is_categorical]

    def _train_one(seg):
        mask = np.asarray([k == seg for k in keys])
        sub_cols = {nm: raw[nm][:n][mask] for nm in frame.names
                    if nm not in seg_cols}
        entry = {"segment": dict(zip(seg_cols, (str(v) for v in seg)))}
        try:
            sub = Frame.from_numpy(
                sub_cols, categorical=[c for c in cats if c not in seg_cols])
            model = builder_cls(**params).train(sub, y=y, x=x)
            entry["status"] = "SUCCEEDED"
            entry["model_key"] = model.key
        except Exception as e:   # per-segment failure is contained
            entry["status"] = "FAILED"
            entry["error"] = str(e)
            log.warning("segment %s failed: %s", seg, e)
        return entry

    if parallelism > 1:
        # the reference's parallel segment builds (SegmentModelsBuilder
        # parallelism); device work serializes inside JAX, but host-side
        # prep/metric phases overlap
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=int(parallelism)) as pool:
            results = list(pool.map(_train_one, uniq))
    else:
        results = [_train_one(seg) for seg in uniq]
    return SegmentModels(make_key("segment_models"), seg_cols, results)
