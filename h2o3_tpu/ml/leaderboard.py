"""Leaderboard — metric-ranked model registry.

Reference: hex/leaderboard/Leaderboard.java — orders models by a
problem-type default metric (AUC desc binomial, mean_per_class_error asc
multinomial, mean_residual_deviance asc regression), preferring
cross-validation metrics, with extra metric columns reported per row.
"""

from __future__ import annotations

from typing import List, Optional

from h2o3_tpu.core.kv import DKV, make_key
from h2o3_tpu.ml.grid import _ASC, default_sort_metric, sort_value

_EXTRA_COLS = {
    "Binomial": ["auc", "logloss", "pr_auc", "mean_per_class_error", "rmse", "mse"],
    "Multinomial": ["mean_per_class_error", "logloss", "rmse", "mse"],
    "Regression": ["mean_residual_deviance", "rmse", "mse", "mae", "rmsle"],
}


class Leaderboard:
    def __init__(self, project_name: str = "default",
                 sort_metric: Optional[str] = None):
        self.key = make_key(f"leaderboard_{project_name}")
        self.project_name = project_name
        self.sort_metric = sort_metric
        self.models: List = []
        DKV.put(self.key, self)

    def add(self, *models):
        for m in models:
            if m is not None and m.key not in {x.key for x in self.models}:
                self.models.append(m)

    def _metric(self) -> str:
        if self.sort_metric:
            return self.sort_metric
        if not self.models:
            return "mse"
        return default_sort_metric(self.models[0])

    def sorted_models(self) -> List:
        metric = self._metric()
        rows = [(sort_value(m, metric), m) for m in self.models]
        rows = [(v, m) for v, m in rows if v is not None]
        reverse = metric.lower() not in _ASC
        return [m for _, m in sorted(rows, key=lambda t: t[0],
                                     reverse=reverse)]

    @property
    def leader(self):
        s = self.sorted_models()
        return s[0] if s else None

    def as_table(self) -> List[dict]:
        """Leaderboard rows (the AutoML leaderboard frame)."""
        if not self.models:
            return []
        cat = self.models[0].output.get("category")
        cols = _EXTRA_COLS.get(cat, _EXTRA_COLS["Regression"])
        out = []
        for m in self.sorted_models():
            row = {"model_id": m.key}
            for c in cols:
                row[c] = sort_value(m, c)
            out.append(row)
        return out

    def __repr__(self):
        lines = [f"Leaderboard[{self.project_name}] "
                 f"(sort: {self._metric()})"]
        for r in self.as_table():
            lines.append("  " + "  ".join(f"{k}={v}" if not isinstance(v, float)
                                          else f"{k}={v:.5g}"
                                          for k, v in r.items()))
        return "\n".join(lines)
