"""Grid search — cartesian + random-discrete hyperparameter walks.

Reference: hex/grid/GridSearch.java:70 (startGridSearch at :662) with
HyperSpaceWalker strategies (Cartesian, RandomDiscrete with max_models /
max_runtime_secs / seed budgets) and the Grid key'd model collection.
Model-parallel training over spare mesh slices is reference parallelism
#5 (SURVEY §2.4). Eligible combos batch through parallel/model_batch.py:
shape buckets (same structural knobs) train as ONE vmapped program and
unstack into ordinary Models, so an M-combo bucket costs one dispatch
instead of M; everything else — and any batched-path failure — walks
the sequential per-combo path, preserving grid semantics, early
stopping, recovery snapshots and leaderboard order exactly.
"""

from __future__ import annotations

import itertools
import os
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu import telemetry
from h2o3_tpu.core.job import Job
from h2o3_tpu.core.kv import DKV, make_key
from h2o3_tpu.parallel import model_batch
from h2o3_tpu.parallel import scheduler as _scheduler
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.grid")

# lower-is-better metrics (hex/ModelMetrics sort contract)
_ASC = {"logloss", "rmse", "mse", "mae", "mean_per_class_error",
        "mean_residual_deviance", "error_rate", "rmsle"}


def stop_early_windowed(scores: List[float], k: int, tol: float,
                        less_is_better: bool) -> bool:
    """ScoreKeeper.stopEarly (hex/ScoreKeeper.java:278): k+1 simple
    moving averages of window k over the last 2k scores (the first
    score is excluded from the length requirement), converged when the
    best new window fails to improve on the reference window by the
    relative tolerance. Reproduces the reference's exact model counts
    (e.g. 2k+1 models for an immediately-flat random grid)."""
    if k <= 0 or len(scores) - 1 < 2 * k:
        return False
    mov = []
    for i in range(k + 1):
        start = len(scores) - 2 * k + i
        m = float(np.mean(scores[start:start + k]))
        if np.isnan(m):
            return False
        mov.append(m)
    last_before, rest = mov[0], mov[1:]
    mn, mx = min(rest), max(rest)
    if less_is_better and last_before == 0.0:
        return True                    # converged to the lower bound
    if np.sign(max(mov)) != np.sign(min(mov)):
        return False                   # zero crossing — not converged
    extreme = mn if less_is_better else mx
    if np.sign(extreme) != np.sign(last_before):
        return False
    ratio = extreme / last_before
    if np.isnan(ratio):
        return False
    return (ratio >= 1 - tol) if less_is_better else (ratio <= 1 + tol)


def sort_value(model, metric: str):
    mmx = model.default_metrics
    d = mmx.to_dict() if hasattr(mmx, "to_dict") else dict(mmx or {})
    aliases = {"auc": "AUC", "gini": "Gini", "rmse": "RMSE", "mse": "MSE",
               "f1": "max_f1", "aucpr": "pr_auc", "residual_deviance":
               "mean_residual_deviance"}
    key = aliases.get(metric.lower(), metric)
    if key not in d and metric in d:
        key = metric
    return d.get(key)


def default_sort_metric(model) -> str:
    cat = model.output.get("category")
    if cat == "Binomial":
        return "auc"
    if cat == "Multinomial":
        return "mean_per_class_error"
    return "mean_residual_deviance"


class Grid:
    """Trained-grid result (hex/grid/Grid.java)."""

    def __init__(self, grid_id: str, models: List, failures: List[dict],
                 sort_metric: str):
        self.grid_id = grid_id
        self.models = models
        self.failures = failures
        self.sort_metric = sort_metric
        DKV.put(grid_id, self)

    @property
    def model_ids(self) -> List[str]:
        return [m.key for m in self.models]

    def sorted_models(self, metric: Optional[str] = None,
                      decreasing: Optional[bool] = None) -> List:
        metric = metric or self.sort_metric
        vals = [(sort_value(m, metric), m) for m in self.models]
        vals = [(v, m) for v, m in vals if v is not None]
        if not vals and self.models:
            # unknown sort metric: keep the models, original order —
            # an empty grid would break clients (get_grid(sort_by=...))
            return list(self.models)
        if decreasing is None:
            decreasing = metric.lower() not in _ASC
        return [m for _, m in sorted(vals, key=lambda t: t[0],
                                     reverse=decreasing)]

    def summary_table(self, metric: Optional[str] = None) -> List[dict]:
        metric = metric or self.sort_metric
        return [{"model_id": m.key, metric: sort_value(m, metric)}
                for m in self.sorted_models(metric)]


class GridSearch:
    """hex/grid/GridSearch.java driver.

    strategy: 'Cartesian' walks the full cross product;
    'RandomDiscrete' samples without replacement under max_models /
    max_runtime_secs budgets (HyperSpaceWalker.RandomDiscreteValueWalker).
    """

    def __init__(self, builder_cls, hyper_params: Dict[str, Sequence],
                 search_criteria: Optional[dict] = None, grid_id: str = None,
                 recovery_dir: Optional[str] = None, **fixed_params):
        self.builder_cls = builder_cls
        # duplicated hyper values are ignored (reference HyperSpaceWalker
        # dedupes the value lists — pyunit_grid_carsGBM contract)
        def _dedup(vals):
            seen, out = set(), []
            for v in vals:
                kv = tuple(v) if isinstance(v, list) else v
                if kv not in seen:
                    seen.add(kv)
                    out.append(v)
            return out
        self.hyper_params = {k: _dedup(list(v))
                             for k, v in hyper_params.items()}
        self.criteria = dict(search_criteria or {"strategy": "Cartesian"})
        self.fixed = fixed_params
        self.grid_id = grid_id or make_key(f"grid_{builder_cls.algo}")
        # hex/faulttolerance/Recovery.java:21-45 — when set, every trained
        # model + the walk state snapshot to this dir so a fresh cluster
        # can resume_grid() the remaining work (core/recovery.py)
        self.recovery_dir = recovery_dir
        self._recovery = None
        if recovery_dir:   # fail fast, not after the first model trains
            from h2o3_tpu.core.recovery import Recovery, ensure_json_safe
            ensure_json_safe(fixed_params, "recovery_dir fixed")
            self._recovery = Recovery(recovery_dir, state_name="grid_state")

    def _combos(self) -> List[dict]:
        names = sorted(self.hyper_params)
        all_combos = [dict(zip(names, vals)) for vals in
                      itertools.product(*(self.hyper_params[n] for n in names))]
        strat = str(self.criteria.get("strategy", "Cartesian")).lower()
        if strat == "randomdiscrete":
            seed = int(self.criteria.get("seed", -1))
            rng = np.random.RandomState(seed if seed >= 0 else None)
            rng.shuffle(all_combos)
            # max_models caps SUCCESSFUL models, enforced in the train
            # walk (failed combos don't count toward it — the reference
            # keeps sampling; pyunit_benign_glm_grid max_models contract)
        return all_combos

    def train(self, training_frame, y: Optional[str] = None,
              x: Optional[Sequence[str]] = None,
              validation_frame=None, _skip_done: Optional[List] = None,
              _prior_models: Optional[List] = None) -> Grid:
        combos = self._combos()
        done = _skip_done or []
        if done:
            # canonical-key set filter: the resume path previously ran
            # an O(n·m) dict-equality scan (`c not in done`) — a 10K-
            # combo grid resumed late paid ~10K·10K dict compares
            done_keys = {model_batch.combo_key(c) for c in done}
            combos = [c for c in combos
                      if model_batch.combo_key(c) not in done_keys]
        budget_s = float(self.criteria.get("max_runtime_secs", 0) or 0)
        max_models = int(self.criteria.get("max_models", 0) or 0)
        stop_rounds = int(self.criteria.get("stopping_rounds", 0) or 0)
        stop_tol = float(self.criteria.get("stopping_tolerance", 1e-3)
                         or 1e-3)
        stop_scores: List[float] = []
        t0 = time.time()
        models = list(_prior_models or [])
        failures: List[dict] = []
        job = Job(f"grid {self.builder_cls.algo}", work=float(len(combos)))
        job.status = "RUNNING"
        # recovery composition (core/recovery.py FitCheckpointer): with
        # a recovery_dir, every sequential combo trains under an in-fit
        # checkpoint scope INSIDE the dir — a SIGKILL mid-combo resumes
        # inside the combo on the next resume_grid(), not at combo start
        fit_dir = (os.path.join(self.recovery_dir, "fit_state")
                   if self.recovery_dir else None)
        # ---- cluster-scheduled / model-batched pre-training ----------
        # eligible combos pre-train ahead of the walk: on a multi-host
        # cloud the work scheduler (parallel/scheduler.py) fans vmap
        # buckets + singleton combos ACROSS hosts (each bucket still
        # vmaps WITHIN its host); otherwise eligible shape buckets train
        # as ONE vmapped program locally. The walk below then consumes
        # the pre-trained models in combo order, so budgets, max_models,
        # asymptotic stopping, recovery snapshots and leaderboard order
        # behave exactly as sequential (models trained past a
        # stop/budget point are discarded).
        pre, sched_all = self._train_scheduled(
            combos, training_frame, y, x, validation_frame, job,
            budget_s=budget_s, t0=t0, max_models=max_models,
            prior=len(models), fit_dir=fit_dir)
        if pre is None:
            pre = self._train_batched(combos, training_frame, y, x,
                                      validation_frame, job,
                                      budget_s=budget_s, t0=t0,
                                      max_models=max_models,
                                      prior=len(models))
        # a fully-scheduled walk only consumes pre-computed results —
        # it must keep draining after a peer death (the whole point of
        # reassignment), so the cloud-health fail-fast stands down
        from h2o3_tpu.core import heartbeat as _hb
        import contextlib as _ctl
        with _hb.local_work_scope() if sched_all else _ctl.nullcontext():
            for i, combo in enumerate(combos):
                if budget_s and time.time() - t0 > budget_s:
                    log.info("grid budget exhausted after %d models",
                             len(models))
                    break
                if max_models and len(models) >= max_models:
                    break
                params = {**self.fixed, **combo}
                try:
                    m = pre.pop(i, None)
                    if isinstance(m, _scheduler.ScheduledFailure):
                        # the owning host's training error, re-raised
                        # here so failure recording matches sequential
                        raise RuntimeError(m.error)
                    if m is None:
                        from h2o3_tpu.core import recovery as _recovery
                        b = self.builder_cls(**params)
                        with _recovery.fit_checkpoint_scope(fit_dir):
                            m = b.train(training_frame, y=y, x=x,
                                        validation_frame=validation_frame)
                    telemetry.counter("grid_models_total",
                                      algo=self.builder_cls.algo).inc()
                    m.output["grid_params"] = combo
                    models.append(m)
                    if self.recovery_dir:
                        self._snapshot(m, combo, done, y, x)
                    if stop_rounds > 0:
                        # asymptotic stopping over the walk's metric
                        # history (HyperSpaceWalker → ScoreKeeper
                        # stopEarly windows)
                        sm = (self.criteria.get("sort_metric")
                              or default_sort_metric(m))
                        v = sort_value(m, sm)
                        if v is not None:
                            stop_scores.append(float(v))
                            if stop_early_windowed(stop_scores,
                                                   stop_rounds, stop_tol,
                                                   sm.lower() in _ASC):
                                log.info("grid stopping criteria met "
                                         "after %d models", len(models))
                                break
                except Exception as e:   # failed combos recorded
                    log.warning("grid combo %s failed: %s", combo, e)
                    failures.append({"params": combo, "error": str(e)})
                job.update(1.0, f"model {i + 1}/{len(combos)}")
        # pre-trained models the walk never consumed (budget/max_models/
        # stopping fired first) are discarded — sequential never trained
        # them, so they must not linger in the store either
        for m in pre.values():
            if not isinstance(m, _scheduler.ScheduledFailure):
                DKV.remove(m.key)
        if fit_dir:
            # the walk completed: unconsumed in-fit snapshots (e.g. a
            # combo that got batch-trained on resume) must not leak
            from h2o3_tpu.core import recovery as _recovery
            _recovery.clear_fit_snapshots(fit_dir)
        job.status = "DONE"
        sort_metric = (self.criteria.get("sort_metric")
                       or (default_sort_metric(models[0]) if models else "mse"))
        return Grid(self.grid_id, models, failures, sort_metric)

    def _train_batched(self, combos: List[dict], training_frame, y, x,
                       validation_frame, job, *, budget_s: float,
                       t0: float, max_models: int, prior: int) -> Dict:
        """Pre-train eligible shape buckets as vmapped programs; returns
        {combo index -> Model}. Any failure or ineligibility leaves the
        affected combos to the sequential walk — this method can only
        ever ADD pre-trained models, never change grid semantics."""
        pre: Dict[int, object] = {}
        if not model_batch.enabled() or len(combos) < 2:
            return pre
        # successes cap: combos past max_models can never enter the grid
        # (failures would shift the window — those walk sequentially)
        planned = combos if not max_models \
            else combos[: max(max_models - prior, 0)]
        algo = self.builder_cls.algo
        for bucket in model_batch.plan_buckets(algo, planned):
            if bucket.width < 2:
                continue            # one model gains nothing from vmap
            if budget_s and time.time() - t0 > budget_s:
                break
            bcombos = [planned[i] for i in bucket.indices]
            try:
                bmodels = model_batch.train_bucket(
                    self.builder_cls, self.fixed, bcombos,
                    training_frame, y=y, x=x,
                    validation_frame=validation_frame)
                pre.update(zip(bucket.indices, bmodels))
            except model_batch.BatchIneligible as e:
                log.debug("grid bucket not batchable (%s): sequential "
                          "fallback", e)
            except Exception as e:   # noqa: BLE001 - fallback boundary
                log.warning("batched %s bucket failed (%s); per-combo "
                            "fallback", algo, e)
            job.update(0.0, "batched buckets")   # cancellation checkpoint
        return pre

    def _train_scheduled(self, combos: List[dict], training_frame, y, x,
                         validation_frame, job, *, budget_s: float,
                         t0: float, max_models: int, prior: int,
                         fit_dir: Optional[str]):
        """Fan combos across cloud hosts (parallel/scheduler.py work
        items): vmap-eligible shape buckets stay bucketed WITHIN a host
        (model batching unchanged) while the scheduler spreads buckets
        + singleton combos ACROSS hosts. Items train on the LOCAL mesh
        against host frame copies and return device-independent model
        bytes; every process then installs the identical result set.

        Returns (pre, covered_all): pre maps combo index → Model |
        ScheduledFailure; (None, False) when the scheduler is off."""
        if not _scheduler.active() or len(combos) < 2:
            return None, False
        # successes cap: combos past max_models can never enter the
        # grid (same planning window as _train_batched)
        planned = combos if not max_models \
            else combos[: max(max_models - prior, 0)]
        if not planned:
            return None, False
        algo = self.builder_cls.algo
        # deterministic item plan — identical on every process (SPMD)
        items: List[tuple] = []
        in_bucket: set = set()
        if model_batch.enabled():
            try:
                for bucket in model_batch.plan_buckets(algo, planned):
                    if bucket.width < 2:
                        continue
                    items.append(("bucket", list(bucket.indices)))
                    in_bucket.update(bucket.indices)
            except Exception as e:   # noqa: BLE001 - plan is best-effort
                log.debug("bucket planning failed (%s); singleton "
                          "items", e)
                items, in_bucket = [], set()
        items.extend(("combo", [i]) for i in range(len(planned))
                     if i not in in_bucket)
        items.sort(key=lambda it: it[1][0])

        def _train_one(ci, lf, lv):
            params = {**self.fixed, **planned[ci]}
            try:
                m = self.builder_cls(**params).train(
                    lf, y=y, x=x, validation_frame=lv)
                return ("model", _scheduler.detach_model(m))
            except Exception as e:   # noqa: BLE001 - travels as failure
                return ("error", str(e))

        def execute(k):
            from h2o3_tpu.parallel import mesh as mesh_mod
            kind, idxs = items[k]
            with mesh_mod.local_mesh_scope():
                lf = training_frame.local_copy()
                lv = (validation_frame.local_copy()
                      if validation_frame is not None else None)
                out = []
                if kind == "bucket":
                    bcombos = [planned[ci] for ci in idxs]
                    bmodels = None
                    try:
                        bmodels = model_batch.train_bucket(
                            self.builder_cls, self.fixed, bcombos, lf,
                            y=y, x=x, validation_frame=lv)
                    except model_batch.BatchIneligible:
                        pass
                    except Exception as e:   # noqa: BLE001 - fallback
                        log.warning("scheduled %s bucket failed (%s); "
                                    "per-combo fallback", algo, e)
                    if bmodels is not None:
                        out.extend(
                            (ci, "model", _scheduler.detach_model(m))
                            for ci, m in zip(idxs, bmodels))
                    else:
                        out.extend((ci,) + _train_one(ci, lf, lv)
                                   for ci in idxs)
                else:
                    out.extend((ci,) + _train_one(ci, lf, lv)
                               for ci in idxs)
            return _scheduler.lower_to_bytes(out)

        deadline = (t0 + budget_s) if budget_s else None
        results = _scheduler.run(f"grid:{algo}:{self.grid_id}",
                                 len(items), execute, job=job,
                                 fit_dir=fit_dir, deadline=deadline)
        pre: Dict[int, object] = {}
        for k in sorted(results):
            rec = results[k]
            if not rec["ok"]:
                for ci in items[k][1]:
                    pre[ci] = _scheduler.ScheduledFailure(rec["error"])
                continue
            for ci, kind, obj in _scheduler.from_bytes(rec["data"]):
                if kind == "error":
                    pre[ci] = _scheduler.ScheduledFailure(obj)
                else:
                    pre[ci] = _scheduler.install_model(obj)
        covered = set(pre) >= set(range(len(combos)))
        return pre, covered

    # -- fault tolerance (hex/faulttolerance/Recovery onModel snapshots) --
    def _snapshot(self, model, combo: dict, done: List[dict],
                  y, x) -> None:
        fname = self._recovery.save_model(model)
        done.append(combo)
        self._model_files = getattr(self, "_model_files", [])
        self._model_files.append(fname)
        self._recovery.write_state({
            "grid_id": self.grid_id,
            "algo": self.builder_cls.algo,
            "fixed": self.fixed,   # validated JSON-serializable in __init__
            "hyper_params": self.hyper_params,
            "criteria": self.criteria,
            "y": y, "x": list(x) if x else None,
            "done": done,
            "models": self._model_files,
        })


def resume_grid(recovery_dir: str, training_frame, validation_frame=None) -> Grid:
    """Resume an interrupted grid from its recovery snapshots
    (hex/faulttolerance/Recovery.onDone re-run path + GridImportExport)."""
    from h2o3_tpu.core.recovery import Recovery
    from h2o3_tpu.models import get_builder
    rec = Recovery(recovery_dir, state_name="grid_state")
    state = rec.read_state()
    if state is None:
        raise FileNotFoundError(
            f"no grid_state.json under {recovery_dir}")
    prior = rec.load_models(state["models"])
    gs = GridSearch(get_builder(state["algo"]), state["hyper_params"],
                    search_criteria=state["criteria"],
                    grid_id=state["grid_id"], recovery_dir=recovery_dir,
                    **state["fixed"])
    gs._model_files = list(state["models"])   # keep prior snapshots listed
    return gs.train(training_frame, y=state["y"], x=state["x"],
                    validation_frame=validation_frame,
                    _skip_done=list(state["done"]), _prior_models=prior)
