"""TreeSHAP — exact per-feature prediction contributions for forests.

Reference: H2O's predict_contributions (hex/Model.java contributions API,
h2o-genmodel tree SHAP in hex/genmodel/algos/tree/TreeSHAP.java — the
Lundberg & Lee "Consistent Individualized Feature Attribution for Tree
Ensembles" algorithm over CompressedTree node weights). Output frame has
one column per feature plus BiasTerm; rows sum to the raw (link-space)
prediction — the same local-accuracy contract the reference guarantees.

TPU-land redesign: our trees are complete binary trees of static depth
(models/tree.py), so node covers pool up from the stored per-leaf
training weights (Tree.leaf_w) instead of being walked out of a
serialized node table. The path recursion (EXTEND/UNWIND) runs on the
host but VECTORIZED over all rows at once — the per-row hot/cold
indicator is the only row-dependent quantity, so every path-weight
update is one numpy broadcast over [N] instead of the reference's
per-row Java recursion.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def _tree_shap_one(feat, thresh, na_left, is_split, leaf, leaf_w,
                   bins, B: int, phi: np.ndarray,
                   cat_split=None, left_words=None) -> float:
    """Accumulate one tree's contributions into phi [N, F]; returns the
    tree's expected value (its BiasTerm share)."""
    D = feat.shape[0]
    N = bins.shape[0]
    # covers[d][l] = training weight reaching node (d, l), pooled from leaves
    covers = [leaf_w.reshape(1 << d, -1).sum(axis=1) for d in range(D)]
    covers.append(leaf_w)
    root_cover = max(float(covers[0][0]), 1e-30)

    # path state: ds/zs host scalars, os/W per-row
    P = D + 2
    ones = np.ones(N, np.float32)

    def extend(ds, zs, os, W, ln, pz, po, pi):
        ds[ln], zs[ln], os[ln] = pi, pz, po
        W[:, ln] = 1.0 if ln == 0 else 0.0
        for i in range(ln - 1, -1, -1):
            W[:, i + 1] += po * W[:, i] * ((i + 1.0) / (ln + 1.0))
            W[:, i] *= pz * ((ln - i) / (ln + 1.0))

    def unwound_sum(zs, os, W, ln, i):
        """Σ weights of the path with element i unwound (leaf use)."""
        o_i, z_i = os[i], zs[i]
        hot = o_i != 0
        o_safe = np.where(hot, o_i, 1.0)
        n = W[:, ln - 1].copy()
        total = np.zeros(N, np.float32)
        for j in range(ln - 2, -1, -1):
            w_hot = n * ln / ((j + 1.0) * o_safe)
            w_cold = W[:, j] * (ln / (z_i * (ln - 1.0 - j)))
            total += np.where(hot, w_hot, w_cold)
            n = W[:, j] - w_hot * (z_i * (ln - 1.0 - j) / ln)
        return total

    def unwind(ds, zs, os, W, ln, i):
        """Remove path element i in place (repeated-feature case)."""
        o_i, z_i = os[i], zs[i]
        hot = o_i != 0
        o_safe = np.where(hot, o_i, 1.0)
        n = W[:, ln - 1].copy()
        for j in range(ln - 2, -1, -1):
            w_hot = n * ln / ((j + 1.0) * o_safe)
            w_cold = W[:, j] * (ln / (z_i * (ln - 1.0 - j)))
            n = W[:, j] - w_hot * (z_i * (ln - 1.0 - j) / ln)
            W[:, j] = np.where(hot, w_hot, w_cold)
        for j in range(i, ln - 1):
            ds[j], zs[j], os[j] = ds[j + 1], zs[j + 1], os[j + 1]

    def recurse(d, l, ds, zs, os, W, ln, pz, po, pi):
        ds, zs, os = list(ds), list(zs), list(os)
        W = W.copy()
        extend(ds, zs, os, W, ln, pz, po, pi)
        ln += 1
        terminal = d == D or not is_split[d, l]
        if terminal:
            v = float(leaf[l << (D - d)])
            for i in range(1, ln):
                s = unwound_sum(zs, os, W, ln, i)
                phi[:, ds[i]] += s * (os[i] - zs[i]) * v
            return
        f = int(feat[d, l])
        b = bins[:, f]
        if cat_split is not None and bool(cat_split[d, l]):
            # categorical subset split: bit membership in the left-set
            lw = left_words[d, l]
            go = (lw[np.clip(b >> 5, 0, lw.shape[0] - 1)]
                  >> (b & 31).astype(np.uint32)) & 1
            go = go.astype(bool)
        else:
            go = b <= thresh[d, l]
        gl = np.where(b == B - 1, bool(na_left[d, l]),
                      go).astype(np.float32)
        r_j = max(float(covers[d][l]), 1e-30)
        r_l = float(covers[d + 1][2 * l])
        r_r = float(covers[d + 1][2 * l + 1])
        iz, io = 1.0, ones
        for k in range(1, ln):
            if ds[k] == f:
                iz, io = zs[k], os[k]
                unwind(ds, zs, os, W, ln, k)
                ln -= 1
                break
        recurse(d + 1, 2 * l, ds, zs, os, W, ln, iz * r_l / r_j, io * gl, f)
        recurse(d + 1, 2 * l + 1, ds, zs, os, W, ln,
                iz * r_r / r_j, io * (1.0 - gl), f)

    ds = [0] * P
    zs = [0.0] * P
    os = [ones] * P
    W = np.zeros((N, P), np.float32)
    recurse(0, 0, ds, zs, os, W, 0, 1.0, ones, -1)
    return float((leaf_w * leaf).sum() / root_cover)


def forest_contributions(forest, bins: np.ndarray, B: int,
                         scale: float = 1.0,
                         row_block: int = 262144) -> np.ndarray:
    """SHAP contributions of a stacked forest → [N, F+1] (last = bias).

    forest: models/tree.py Tree with leading tree axis; bins [N, F] host
    int bin codes (rebin_for_scoring output); scale multiplies every
    tree's output (1/T for DRF vote averaging).
    """
    feat = np.asarray(forest.feat)
    thresh = np.asarray(forest.thresh)
    na_left = np.asarray(forest.na_left)
    is_split = np.asarray(forest.is_split)
    leaf = np.asarray(forest.leaf, np.float64) * scale
    leaf_w = np.asarray(forest.leaf_w, np.float64)
    cat_split = np.asarray(forest.cat_split)
    left_words = np.asarray(forest.left_words)
    T = feat.shape[0]
    N, F = bins.shape
    out = np.zeros((N, F + 1), np.float64)
    for lo in range(0, N, row_block):
        hi = min(N, lo + row_block)
        blk = np.ascontiguousarray(bins[lo:hi])
        phi = np.zeros((hi - lo, F), np.float32)
        bias = 0.0
        for t in range(T):
            bias += _tree_shap_one(feat[t], thresh[t], na_left[t],
                                   is_split[t], leaf[t], leaf_w[t],
                                   blk, B, phi,
                                   cat_split=cat_split[t],
                                   left_words=left_words[t])
        out[lo:hi, :F] = phi
        out[lo:hi, F] = bias
    return out


def contributions_frame(model, frame, forest=None, scale: float = 1.0,
                        bias_offset: float = 0.0):
    """Shared GBM/DRF predict_contributions → Frame(features…, BiasTerm).

    Only Regression and Binomial models are supported — the reference's
    contract (hex/Model.java rejects multinomial contributions).
    """
    from h2o3_tpu.frame.binning import rebin_for_scoring
    from h2o3_tpu.frame.frame import Frame

    cat = str(model.output.get("category"))
    if cat not in ("Regression", "Binomial"):
        raise ValueError(
            "predict_contributions supports only regression and binomial "
            f"models (got {cat})")
    bm = rebin_for_scoring(model.bm, frame)
    bins = np.asarray(bm.bins)[: frame.nrows]
    phi = forest_contributions(forest if forest is not None else model.forest,
                               bins, model.bm.nbins_total, scale=scale)
    phi[:, -1] += bias_offset
    names = list(model.output["names"])
    cols: Dict[str, np.ndarray] = {
        n: phi[:, j] for j, n in enumerate(names)}
    cols["BiasTerm"] = phi[:, -1]
    return Frame.from_numpy(cols)
