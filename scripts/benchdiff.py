#!/usr/bin/env python3
"""benchdiff — diff two BENCH_*.json artifacts / perf-baseline snapshots
into a pass/fail table with per-phase deltas.

BENCH_r01–r05 exist but nothing ever compared them; this is the offline
half of the perf-regression guard (telemetry/perfbase.py is the
in-process half). Pure stdlib — runs anywhere, jax-free, in well under
a second (the scripts/tier1.sh ``perfguard`` target runs it against the
committed BENCH_r05.json on every capped CI run).

Accepted inputs (auto-detected per file):

* a driver BENCH artifact: ``{"n", "cmd", "rc", "tail", ...}`` — metric
  lines are the JSON objects embedded one-per-line in ``tail``, parsed
  only up to the ``# ---- summary`` re-print (which would double-count)
  and deduped by metric name (first wins);
* a bare list of metric objects, or ``{"metrics": [...]}``;
* a ``telemetry/perfbase.py`` baseline file, or a directory of them.

Comparison: metrics present in BOTH sides with a numeric ``value``.
Direction comes from ``unit`` — ``*/sec*`` means higher is better,
``seconds`` means lower is better. A change worse than ``--threshold``
(default 0.25, the ≥25% SLO bound) is a REGRESSION and the exit code is
nonzero; an identical pair (or a pair with no comparable metrics — e.g.
two all-error r05 runs) passes with exit 0. When both sides carry a
``phases`` dict the per-phase deltas print alongside, so a regression
says WHERE the step got slower (host vs compute vs collective wait).

Usage:
    python scripts/benchdiff.py OLD NEW [--threshold 0.25] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

SUMMARY_MARK = "# ---- summary"


# ------------------------------------------------------------- loading


def _metrics_from_tail(tail: str) -> List[Dict]:
    """JSON metric lines out of a BENCH artifact's stdout tail, stopping
    at the tail-proof summary and deduping by metric (first wins)."""
    out: List[Dict] = []
    seen = set()
    for ln in tail.splitlines():
        ln = ln.strip()
        if ln.startswith(SUMMARY_MARK):
            break
        if not ln.startswith("{"):
            continue
        try:
            obj = json.loads(ln)
        except ValueError:
            continue
        name = obj.get("metric")
        if not isinstance(obj, dict) or not name or name in seen:
            continue
        seen.add(name)
        out.append(obj)
    return out


def _normalize(doc) -> Optional[List[Dict]]:
    """One loaded JSON document → a metric list, or None if unknown."""
    if isinstance(doc, list):
        return [m for m in doc if isinstance(m, dict) and "metric" in m]
    if not isinstance(doc, dict):
        return None
    if "tail" in doc:                       # driver BENCH artifact
        return _metrics_from_tail(str(doc.get("tail") or ""))
    if isinstance(doc.get("metrics"), list):
        return _normalize(doc["metrics"])
    if "metric" in doc:
        return [doc]
    if "best_step_seconds" in doc:          # perfbase baseline file
        hist = doc.get("history") or []
        return [{"metric": doc.get("key", "baseline"),
                 "value": float(doc.get("last_step_seconds")
                                or doc.get("best_step_seconds") or 0),
                 "unit": "seconds",
                 "phases": dict((hist[-1].get("phases") or {})
                                if hist else {})}]
    return None


def load_metrics(path: str) -> List[Dict]:
    """Metric list from a file or a perf-baseline directory."""
    if os.path.isdir(path):
        out: List[Dict] = []
        for name in sorted(os.listdir(path)):
            if name.endswith(".json"):
                out.extend(load_metrics(os.path.join(path, name)))
        return out
    with open(path) as f:
        doc = json.load(f)
    metrics = _normalize(doc)
    if metrics is None:
        raise ValueError(f"{path}: unrecognized benchdiff input format")
    return metrics


# ------------------------------------------------------------ comparing


def _higher_is_better(unit: str) -> bool:
    u = (unit or "").lower()
    if "/sec" in u or u.endswith("/s"):
        return True
    if "second" in u or u == "s":
        return False
    return True


def compare(old: List[Dict], new: List[Dict],
            threshold: float = 0.25) -> Dict:
    """Pass/fail verdict over the metrics present in both sides.

    Returns {"rows": [...], "regressions": [names], "compared": n,
    "ok": bool}; ok is True when nothing regressed past the threshold —
    including the degenerate no-comparable-metrics case (two identical
    all-error runs must pass, not crash)."""
    old_by = {m["metric"]: m for m in old
              if isinstance(m.get("value"), (int, float))}
    rows: List[Dict] = []
    regressions: List[str] = []
    for m in new:
        name = m.get("metric")
        v_new = m.get("value")
        base = old_by.get(name)
        if base is None or not isinstance(v_new, (int, float)):
            continue
        v_old = float(base["value"])
        unit = str(m.get("unit") or base.get("unit") or "")
        hib = _higher_is_better(unit)
        delta = (float(v_new) - v_old) / abs(v_old) if v_old else 0.0
        worse = -delta if hib else delta
        regressed = worse > threshold
        row = {"metric": name, "old": v_old, "new": float(v_new),
               "unit": unit, "delta_pct": round(delta * 100.0, 2),
               "regressed": regressed}
        op, np_ = base.get("phases"), m.get("phases")
        if isinstance(op, dict) and isinstance(np_, dict):
            row["phase_deltas"] = {
                p: round(float(np_.get(p, 0.0)) - float(op.get(p, 0.0)),
                         6)
                for p in sorted(set(op) | set(np_))}
        rows.append(row)
        if regressed:
            regressions.append(name)
    return {"rows": rows, "regressions": regressions,
            "compared": len(rows), "ok": not regressions,
            "threshold": threshold}


# ------------------------------------------------------------- printing


def _fmt_row(r: Dict) -> str:
    flag = "FAIL" if r["regressed"] else "ok"
    line = (f"  [{flag:4s}] {r['metric'][:58]:58s} "
            f"{r['old']:>12.4g} -> {r['new']:>12.4g} "
            f"{r['unit']:<14s} {r['delta_pct']:+7.1f}%")
    if r.get("phase_deltas"):
        deltas = "  ".join(f"{p}{d:+.3f}s"
                           for p, d in r["phase_deltas"].items() if d)
        if deltas:
            line += f"\n         phases: {deltas}"
    return line


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old", help="baseline BENCH_*.json / baseline dir")
    ap.add_argument("new", help="candidate BENCH_*.json / baseline dir")
    ap.add_argument("--threshold", type=float, default=0.25,
                    help="regression bound as a fraction (default 0.25)")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict as one JSON object")
    args = ap.parse_args(argv)
    try:
        old = load_metrics(args.old)
        new = load_metrics(args.new)
    except (OSError, ValueError) as e:
        print(f"benchdiff: {e}", file=sys.stderr)
        return 2
    verdict = compare(old, new, threshold=args.threshold)
    if args.json:
        print(json.dumps(verdict, indent=1))
    else:
        print(f"benchdiff: {args.old} -> {args.new} "
              f"({verdict['compared']} comparable metrics, "
              f"threshold {args.threshold:.0%})")
        for r in verdict["rows"]:
            print(_fmt_row(r))
        if not verdict["rows"]:
            print("  (no comparable metrics — pass by vacuity)")
        print(f"benchdiff: {'PASS' if verdict['ok'] else 'FAIL'} "
              f"({len(verdict['regressions'])} regression(s))")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
