#!/usr/bin/env bash
# tier1.sh — the blessed tier-1 entry points.
#
# The full tier-1 suite does not fit the 870s per-invocation cap on the
# ~1.8x-slow CI container, which used to force ad-hoc hand-picked
# two-part runs. This script splits the suite DETERMINISTICALLY:
# `tests/test_*.py` are sorted lexically and alternated by index, and
# the `-m multiprocess` pod legs (real 2-process gloo clouds — minutes
# each, clustered in a few files) are carved out into their own target
# so neither half busts the cap as pods are added. The three targets
# together cover exactly the whole suite.
#
#   scripts/tier1.sh part1        # even-indexed files, minus pod legs
#   scripts/tier1.sh part2        # odd-indexed files, minus pod legs
#   scripts/tier1.sh multiprocess # pod smoke: ONLY -m multiprocess legs
#                                 # (cloud formation, durability, fleet,
#                                 # tracing, global fit)
#   scripts/tier1.sh full         # the ROADMAP.md one-shot (needs >870s)
#   scripts/tier1.sh perfguard    # benchdiff gate vs committed BENCH
#                                 # snapshot (jax-free, <10s)
#
# Every mode mirrors the ROADMAP.md tier-1 flags exactly; each capped
# mode runs under `timeout -k 10 870`.
set -u -o pipefail

cd "$(dirname "$0")/.."
MODE="${1:-full}"

PYTEST=(env JAX_PLATFORMS=cpu python -m pytest -q \
        --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly)

mapfile -t ALL < <(ls tests/test_*.py | sort)

half() {  # half <parity>: every 2nd file starting at index $1
    local parity="$1" i
    for i in "${!ALL[@]}"; do
        if (( i % 2 == parity )); then printf '%s\n' "${ALL[$i]}"; fi
    done
}

case "$MODE" in
    part1|part2)
        parity=0; [[ "$MODE" == part2 ]] && parity=1
        mapfile -t FILES < <(half "$parity")
        echo "# tier1 $MODE: ${#FILES[@]}/${#ALL[@]} test files" >&2
        timeout -k 10 870 "${PYTEST[@]}" \
            -m 'not slow and not multiprocess' "${FILES[@]}"
        ;;
    full)
        timeout -k 10 870 "${PYTEST[@]}" -m 'not slow' tests/
        ;;
    multiprocess)
        timeout -k 10 870 "${PYTEST[@]}" -m 'multiprocess and not slow' \
            tests/
        ;;
    perfguard)
        # perf-regression gate (ISSUE 20): diff the committed BENCH
        # snapshot against itself through scripts/benchdiff.py — proves
        # the gate's parse/compare path end-to-end, jax-free, <10s.
        # An identical pair MUST pass; a broken parser fails loudly.
        timeout -k 10 60 env JAX_PLATFORMS='' python \
            scripts/benchdiff.py BENCH_r05.json BENCH_r05.json
        ;;
    *)
        echo "usage: $0 {part1|part2|full|multiprocess|perfguard}" >&2
        exit 2
        ;;
esac
