"""Headline benchmark: GBM histogram training throughput on TPU.

Mirrors BASELINE.json config #1 (GBM binomial, 50 trees, depth 6,
airlines-like schema). Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline: the reference publishes no GBM numbers in-tree
(BASELINE.md); the comparison constant below is an estimate of H2O-3 GBM
single-node CPU throughput on this shape (dual-Xeon class, ~1M
rows/sec·iteration across 50 iterations), derived from the reference's
own DL throughput scaling notes (hex/deeplearning/README.md) and public
H2O GBM benchmarks. Replace with a measured number when a JVM reference
run is available.
"""

import json
import time

import numpy as np

REFERENCE_ROWS_PER_SEC = 1.0e6  # estimated H2O-3 single-node CPU GBM

N_ROWS = 1_000_000
N_NUM = 20
N_CAT = 8
NTREES = 50
DEPTH = 6


def main():
    import jax
    import h2o3_tpu
    from h2o3_tpu.models.gbm import GBMEstimator

    h2o3_tpu.init()
    r = np.random.RandomState(0)
    cols = {f"n{i}": r.randn(N_ROWS).astype(np.float32) for i in range(N_NUM)}
    for i in range(N_CAT):
        cols[f"c{i}"] = r.randint(0, 30, N_ROWS).astype(np.float64)
    logits = cols["n0"] * 1.5 + cols["n1"] - (cols["c0"] > 15) * 0.8
    y = (r.rand(N_ROWS) < 1 / (1 + np.exp(-logits))).astype(int)
    cols["dep_delayed"] = np.array(["N", "Y"], object)[y]
    fr = h2o3_tpu.Frame.from_numpy(
        cols, categorical=[f"c{i}" for i in range(N_CAT)] + ["dep_delayed"])

    # warmup at the FULL config: the boosting scans chunk at 10 trees,
    # but the scoring/metrics programs (predict_forest) specialize on the
    # total forest size, so only an ntrees=NTREES run compiles everything
    # the timed run executes
    GBMEstimator(ntrees=NTREES, max_depth=DEPTH, seed=1).train(
        fr, y="dep_delayed")

    t0 = time.time()
    model = GBMEstimator(ntrees=NTREES, max_depth=DEPTH, seed=1).train(
        fr, y="dep_delayed")
    dt = time.time() - t0

    rows_per_sec = N_ROWS * NTREES / dt
    print(json.dumps({
        "metric": f"GBM-{NTREES}trees-d{DEPTH} training throughput "
                  f"({N_ROWS / 1e6:.0f}M rows, {N_NUM + N_CAT} features)",
        "value": round(rows_per_sec, 1),
        "unit": "rows/sec/chip",
        "vs_baseline": round(rows_per_sec / REFERENCE_ROWS_PER_SEC, 3),
        "train_seconds": round(dt, 2),
        "auc": round(model.training_metrics["AUC"], 4),
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
