"""Benchmark suite: the five BASELINE.json configs, one JSON line each.

    python bench.py            # all five configs under the time budget
    python bench.py gbm        # one config by substring
    python bench.py --one gbm  # run one config in-process (child mode)
    python bench.py --probe    # backend liveness probe (child mode)
    H2O3TPU_BENCH_FAST=1       # scaled-down shapes (CI smoke)
    H2O3TPU_BENCH_BUDGET_S=N   # wallclock budget (default 1500s)
    H2O3TPU_BENCH_FULL=1       # force the 50M-row GBM escalation
    H2O3TPU_BENCH_CONFIG_TIMEOUT_S=N  # per-config hard cap override
    H2O3TPU_BENCH_TRACE_DIR=DIR       # Chrome-trace artifacts per config
                                      # (default /tmp/h2o3tpu_bench_traces)

Structure (round-3 contract): the flagship GBM line is emitted FIRST at
a scale that finishes in minutes; every other config is bounded; the
50M-row GBM escalation runs LAST and only if the remaining budget
allows.

Fault tolerance (round-5 lesson — BENCH_r05 banked ZERO lines when the
first device_put hit a wedged TPU worker and the in-place retry hit the
corpse again until the budget went to -22s): the parent process never
touches the backend. Each config runs in a FRESH CHILD process with a
hard per-config timeout, preceded by a backend liveness probe
(core/watchdog.py probe, itself a subprocess) under the shared
bounded-backoff retry policy. A wedged worker therefore costs one
config line, not the scoreboard, and the budget is clamped at zero.

Configs (BASELINE.json):
  1. gbm      GBM binomial 100 trees depth 6, airlines schema 5M rows
              (+50M escalation when budget allows), ingest included.
  2. glm      GLM binomial IRLS + L-BFGS, HIGGS-shape 11M x 28.
  3. dl       DeepLearning MLP [200,200] rectifier, MNIST shape — the one
              config with a PUBLISHED reference number (80K samples/sec
              single node, hex/deeplearning/README.md:26-34).
  4. xgb      XGBoost-facade hist trees, airlines schema 5M rows.
  5. automl   H2OAutoML max_models=20 wallclock, airlines 500K rows,
              bounded by max_runtime_secs.

vs_baseline: config 3 compares against the published 80K samples/sec.
The others carry ESTIMATED single-node JVM numbers (the reference
publishes none in-tree — BASELINE.md): GBM 1.0e6 rows/sec·tree, GLM
1.0e7 row-iters/sec, XGBoost 2.0e6 rows/sec·tree, AutoML est. 300s
wallclock for the same config. Estimates are marked in the output.
"""

import json
import os
import sys
import time

import numpy as np

FAST = os.environ.get("H2O3TPU_BENCH_FAST") == "1"
# stub mode (tests): tiny stdlib-only configs exercise the parent
# harness — subprocess isolation, timeouts, probes, budget clamping —
# without booting a backend (tests/test_bench_harness.py)
STUB = os.environ.get("H2O3TPU_BENCH_STUB") == "1"
BUDGET_S = float(os.environ.get("H2O3TPU_BENCH_BUDGET_S", "1500"))
_T0 = time.time()

# infra-class error signatures: transient failures of the compile
# service / tunneled chip, NOT user errors (superset of
# watchdog.INFRA_SIGNS — kept inline so the parent can classify a
# child's stderr without importing anything heavy)
_INFRA_SIGNS = ("remote_compile", "INTERNAL", "UNAVAILABLE",
                "DEADLINE_EXCEEDED", "RESOURCE_EXHAUSTED: Attempting")


def _remaining() -> float:
    """Wallclock budget left, clamped at zero: a config that overruns
    its estimate must not drive the recorded budget negative (the
    round-5 scoreboard showed -22s)."""
    return max(0.0, BUDGET_S - (time.time() - _T0))


# ---------------------------------------------------------------- helpers


_EMITTED = []    # every metric line, re-printed at exit (tail-proof)


def _emit_raw(line):
    _EMITTED.append(line)
    print(json.dumps(line), flush=True)


def _emit(metric, value, unit, vs_baseline, baseline_kind, **extra):
    line = {"metric": metric, "value": round(value, 1), "unit": unit,
            "vs_baseline": round(vs_baseline, 3),
            "baseline": baseline_kind}
    line.update(extra)
    _emit_raw(line)


def _print_summary():
    # tail-proof summary: the driver captures only the END of stdout, and
    # round 3 lost its flagship GBM/GLM/DL lines to scroll-off — re-print
    # every metric line as the very last output so the tail always has
    # all of them (VERDICT r3 weak #9). Registered via atexit so a
    # driver SIGTERM/exception mid-config still flushes what exists.
    if _EMITTED:
        print("# ---- summary: all metric lines (re-printed, tail-proof) "
              "----", flush=True)
        for line in _EMITTED:
            print(json.dumps(line), flush=True)
        _EMITTED.clear()


def _airlines_csv(n_rows: int) -> str:
    """Write (once) an airlines-schema CSV of n_rows to /tmp; returns path.

    Real on-disk data so the bench includes the ingest path (streaming
    CSV → HBM)."""
    path = f"/tmp/h2o3tpu_airlines_{n_rows}.csv"
    if os.path.exists(path):
        return path
    r = np.random.RandomState(7)
    carriers = np.array(["UA", "AA", "DL", "WN", "US", "NW", "CO", "MQ"])
    origins = np.array([f"{a}{b}{c}" for a in "ABCDE" for b in "AEIOU"
                        for c in "KLMNP"])
    # pyarrow csv writer over dictionary-encoded string columns: the
    # strings are never materialized host-side (~80 MB/s vs ~6 for
    # object arrays) — the 50M-row (2.4GB) file must not eat the bench
    # budget in generation (round-4 gbm-full skip)
    import pyarrow as pa
    import pyarrow.csv as pacsv

    def _dict(idx, values):
        return pa.DictionaryArray.from_arrays(
            pa.array(idx, type=pa.int32()), pa.array(list(values)))

    chunk = 2_000_000
    t0 = time.time()
    sink = open(path + ".tmp", "wb")
    writer = None
    for lo in range(0, n_rows, chunk):
        n = min(chunk, n_rows - lo)
        dep = r.randint(0, 2400, n)
        crs = np.maximum(dep - r.randint(-10, 60, n), 0)
        month = r.randint(1, 13, n)
        car_i = r.randint(0, len(carriers), n)
        # learnable signal: late-day departures + carrier/origin effects
        delay = (0.03 * (dep - 1000)
                 + np.isin(car_i, [0, 5]) * 15          # UA, NW
                 + np.isin(month, [12, 1, 6]) * 8
                 + r.randn(n) * 25)
        cols = {
            "Year": pa.array(r.randint(1987, 2009, n)),
            "Month": pa.array(month),
            "DayofMonth": pa.array(r.randint(1, 29, n)),
            "DayOfWeek": pa.array(r.randint(1, 8, n)),
            "DepTime": pa.array(dep),
            "CRSDepTime": pa.array(crs),
            "UniqueCarrier": _dict(car_i, carriers),
            "Origin": _dict(r.randint(0, len(origins), n), origins),
            "Dest": _dict(r.randint(0, len(origins), n), origins),
            "Distance": pa.array(r.randint(50, 2600, n)),
            "IsDepDelayed": _dict((delay > 15).astype(np.int32),
                                  ["NO", "YES"]),
        }
        tbl = pa.table(cols)
        if writer is None:
            writer = pacsv.CSVWriter(sink, tbl.schema)
        writer.write_table(tbl)
    writer.close()
    sink.close()
    os.rename(path + ".tmp", path)
    print(f"# wrote {path} ({os.path.getsize(path)/1e9:.2f} GB) "
          f"in {time.time()-t0:.0f}s", file=sys.stderr)
    return path


def _tree_mfu_pct(rows_per_sec_tree: float, depth: int, n_features: int,
                  n_bins: int = 65) -> float:
    """MFU of the histogram matmuls (the tree FLOPs that touch the MXU):
    per row per tree, levels 0..depth-1 contract [3L,C]x[C,F*B] with
    L=2^level nodes -> 2 * 3*(2^depth - 1) * F*B flops (ops/histogram.py
    _block_hist), against the v5e bf16 peak 197 TFLOP/s."""
    flops_per_row_tree = 2 * 3 * (2 ** depth - 1) * n_features * n_bins
    return 100 * rows_per_sec_tree * flops_per_row_tree / 197e12


def _hbm_peak():
    import jax
    try:
        s = jax.devices()[0].memory_stats() or {}
        return int(s.get("peak_bytes_in_use", 0) or 0)
    except Exception:
        return 0


def _compile_count() -> int:
    """Process-wide XLA backend compiles so far (telemetry registry).

    Emitted per config as a DELTA over the timed run: a warmed run
    should report compiles_timed=0 — anything else means the timed
    number includes compiler wall time, the exact failure mode the
    telemetry subsystem exists to expose."""
    from h2o3_tpu import telemetry
    return int(telemetry.REGISTRY.value("xla_compile_total"))


def _roofline_fields(algo):
    """Hardware-relative axis per config (telemetry/roofline.py): the
    last fit's MFU and HBM-bandwidth utilization as FRACTIONS of the
    detected device peaks — BENCH rounds become comparable across
    backends, not just across rows/sec. Rides the step-profiler phase
    breakdown (telemetry/stepprof.py) along: every BENCH line says not
    just how fast but WHERE the step wall-clock went."""
    out = {}
    try:
        from h2o3_tpu.telemetry import roofline
        f = roofline.last_fit(algo)
        out.update({"mfu": round(f["mfu"], 6),
                    "hbm_util": round(f["hbm_util"], 6)})
    except Exception:   # noqa: BLE001 - accounting must never fail a config
        pass
    try:
        from h2o3_tpu.telemetry import stepprof
        ph = stepprof.last_fit_phases(algo)
        if ph.get("phases"):
            out["phases"] = ph["phases"]
            out["collective_share"] = ph.get("collective_share", 0.0)
    except Exception:   # noqa: BLE001
        pass
    return out


# ---------------------------------------------------------------- configs


def _gbm_at(n_rows: int, ntrees: int, depth: int, tag: str):
    from h2o3_tpu.core.kv import DKV
    from h2o3_tpu.io.stream import stream_import_csv
    from h2o3_tpu.models.gbm import GBMEstimator
    path = _airlines_csv(n_rows)
    # warm the transfer/dispatch machinery on a 2K-row slice so the
    # ingest number measures STREAMING rate, not one-time process setup
    # (first device_put etc. cost ~9s of pure init in a fresh process)
    wpath = "/tmp/h2o3tpu_ingest_warmup.csv"
    with open(path) as fsrc, open(wpath, "w") as fdst:
        for _ in range(2001):
            ln = fsrc.readline()
            if not ln:
                break
            fdst.write(ln)
    wfr = stream_import_csv(wpath)
    DKV.remove(wfr.key)
    t0 = time.time()
    fr = stream_import_csv(path)
    t_ingest = time.time() - t0
    # warmup: boosting runs as compiled scans over 25-tree chunks, so a
    # 25-tree train on the SAME frame compiles the exact program the
    # timed run reuses — no second full-scale train needed (the round-2
    # double-train blew the driver window)
    wm = GBMEstimator(ntrees=min(25, ntrees), max_depth=depth,
                      seed=1).train(fr, y="IsDepDelayed")
    DKV.remove(wm.key)
    del wm
    c0 = _compile_count()
    t1 = time.time()
    model = GBMEstimator(ntrees=ntrees, max_depth=depth, seed=1).train(
        fr, y="IsDepDelayed")
    t_train = time.time() - t1
    rows_per_sec = n_rows * ntrees / t_train
    _emit(
        f"GBM-{ntrees}trees-d{depth} airlines {n_rows/1e6:.0f}M rows "
        f"({tag}; streamed CSV ingest + train)",
        rows_per_sec, "rows/sec/chip",
        rows_per_sec / 1.0e6, "estimated JVM 1.0e6 rows/sec-tree",
        ingest_seconds=round(t_ingest, 1),
        ingest_mb_per_sec=round(os.path.getsize(path) / 1e6 / t_ingest, 1),
        train_seconds=round(t_train, 1),
        total_seconds=round(t_ingest + t_train, 1),
        auc=round(float(model.training_metrics["AUC"]), 4),
        mfu_pct=round(_tree_mfu_pct(rows_per_sec, depth, 10), 2),
        peak_hbm_gb=round(_hbm_peak() / 1e9, 2),
        compiles_timed=_compile_count() - c0,
        compiles_total=_compile_count(),
        **_roofline_fields("gbm"))


def bench_gbm():
    """Flagship line, emitted FIRST and sized to finish in minutes."""
    n_rows = 1_000_000 if FAST else 5_000_000
    _gbm_at(n_rows, ntrees=100, depth=6, tag="flagship")


def bench_gbm_full():
    """North-star-scale escalation; runs LAST, only under budget."""
    n_rows = 5_000_000 if FAST else 50_000_000
    _gbm_at(n_rows, ntrees=100, depth=6, tag="north-star scale")


def bench_glm():
    import h2o3_tpu
    from h2o3_tpu.models.glm import GLMEstimator
    n = 1_000_000 if FAST else 11_000_000
    p = 28
    r = np.random.RandomState(3)
    X = r.randn(n, p).astype(np.float32)
    beta = r.randn(p) * 0.3
    yv = (r.rand(n) < 1 / (1 + np.exp(-(X @ beta)))).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(p)}
    cols["y"] = np.array(["b", "s"], object)[yv]
    fr = h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])
    del X

    for solver, max_it in (("irlsm", 8), ("l_bfgs", 40)):
        est = GLMEstimator(family="binomial", solver=solver, lambda_=0.0,
                           max_iterations=max_it, standardize=True)
        est.train(fr, y="y")          # warmup/compile
        c0 = _compile_count()
        t0 = time.time()
        m = GLMEstimator(family="binomial", solver=solver, lambda_=0.0,
                         max_iterations=max_it,
                         standardize=True).train(fr, y="y")
        dt = time.time() - t0
        row_iters = n * max_it / dt
        # MFU: IRLSM is Gram-dominated (2*n*p^2 per iter, ops/gram.py);
        # L-BFGS is two matvec passes (4*n*p per iter). Both shapes are
        # HBM-bandwidth-bound at p=28, so these run low by design.
        flops_per_row_iter = 2 * p * p if solver == "irlsm" else 4 * p
        _emit(
            f"GLM binomial {solver.upper()} HIGGS-shape {n/1e6:.0f}Mx{p}",
            row_iters, "row-iters/sec/chip",
            row_iters / 1.0e7, "estimated JVM 1.0e7 row-iters/sec",
            train_seconds=round(dt, 2),
            mfu_pct=round(100 * row_iters * flops_per_row_iter / 197e12, 3),
            auc=round(float(m.training_metrics["AUC"]), 4),
            compiles_timed=_compile_count() - c0,
            peak_hbm_gb=round(_hbm_peak() / 1e9, 2),
            **_roofline_fields("glm"))


def bench_dl():
    import h2o3_tpu
    from h2o3_tpu.models.deeplearning import DeepLearningEstimator
    n = 100_000 if FAST else 1_000_000
    d = 784                      # MNIST shape → published 80K/s baseline
    epochs = 2.0 if FAST else 8.0   # enough steps to amortize the
    #                                 per-chunk host sync (~0.12s RTT)
    r = np.random.RandomState(5)
    X = (r.rand(n, d) > 0.8).astype(np.float32)
    yv = r.randint(0, 10, n)
    cols = {f"p{i}": X[:, i] for i in range(d)}
    cols["label"] = yv.astype(str)
    fr = h2o3_tpu.Frame.from_numpy(cols, categorical=["label"])
    del X, cols

    # warmup compiles the SAME programs the timed run uses (the fused
    # chunk is a fixed-size program with a traced step limit, so any
    # epoch count shares it)
    DeepLearningEstimator(hidden=[200, 200], activation="rectifier",
                          epochs=0.1, seed=1).train(fr, y="label")
    c0 = _compile_count()
    t0 = time.time()
    m = DeepLearningEstimator(hidden=[200, 200], activation="rectifier",
                              epochs=epochs, seed=1).train(fr, y="label")
    dt = time.time() - t0
    sps = n * epochs / dt
    # MFU: 6 flops per weight per sample (fwd 2 + bwd 4) over the three
    # dense layers, against the v5e bf16 peak (197 TFLOP/s)
    params = d * 200 + 200 * 200 + 200 * 10
    mfu = sps * 6 * params / 197e12
    # convergence proof rides the line: training classification error
    # must beat the 10-class prior (0.9) by a wide margin
    err = None
    for k in ("error_rate", "err", "mean_per_class_error"):
        try:
            err = round(float(m.training_metrics[k]), 4)
            break
        except Exception:
            continue
    _emit(
        f"DeepLearning [200,200] rectifier MNIST-shape {n/1e6:.1f}M",
        sps, "samples/sec/chip",
        sps / 80_000.0, "PUBLISHED 80K samples/sec 1-node "
        "(hex/deeplearning/README.md:26)",
        train_seconds=round(dt, 2), mfu_pct=round(100 * mfu, 2),
        train_err=err,
        compiles_timed=_compile_count() - c0,
        peak_hbm_gb=round(_hbm_peak() / 1e9, 2),
        **_roofline_fields("deeplearning"))


def bench_xgb():
    from h2o3_tpu.io.stream import stream_import_csv
    from h2o3_tpu.models.xgboost import XGBoostEstimator
    n_rows = 1_000_000 if FAST else 5_000_000
    ntrees = 50
    fr = stream_import_csv(_airlines_csv(n_rows))
    XGBoostEstimator(ntrees=5, max_depth=6, seed=1).train(
        fr, y="IsDepDelayed")
    c0 = _compile_count()
    t0 = time.time()
    m = XGBoostEstimator(ntrees=ntrees, max_depth=6, seed=1).train(
        fr, y="IsDepDelayed")
    dt = time.time() - t0
    rps = n_rows * ntrees / dt
    _emit(
        f"XGBoost-facade hist {ntrees}trees airlines {n_rows/1e6:.0f}M",
        rps, "rows/sec/chip",
        rps / 2.0e6, "estimated JVM xgboost-hist 2.0e6 rows/sec-tree",
        train_seconds=round(dt, 2),
        mfu_pct=round(_tree_mfu_pct(rps, 6, 10), 2),
        auc=round(float(m.training_metrics["AUC"]), 4),
        compiles_timed=_compile_count() - c0,
        peak_hbm_gb=round(_hbm_peak() / 1e9, 2),
        **_roofline_fields("xgboost"))


def bench_sort():
    """Device radix-order path: 10M-row two-key sort + single-key merge
    (water/rapids/RadixOrder + BinaryMerge roles)."""
    import h2o3_tpu
    from h2o3_tpu.ops.sort import device_sort
    from h2o3_tpu.rapids import _device_merge
    n = 1_000_000 if FAST else 10_000_000
    r = np.random.RandomState(11)
    fr = h2o3_tpu.Frame.from_numpy({
        "k": r.randint(0, n // 2, n).astype(float),
        "b": r.randn(n), "v": np.arange(n, dtype=float)})
    import jax.numpy as jnp
    w = device_sort(fr, ["k", "b"], [True, True])  # warmup/compile
    float(jnp.sum(w.col("k").data))   # force completion (tunnel-safe sync)
    for c in w.names:                 # drain every async column gather
        float(jnp.sum(w.col(c).data))
    t0 = time.time()
    out = device_sort(fr, ["k", "b"], [True, True])
    for c in out.names:
        float(jnp.sum(out.col(c).data))
    dt = time.time() - t0
    _emit(f"Sort 2-key {n/1e6:.0f}M rows (device radix-order)",
          n / dt, "rows/sec/chip",
          (n / dt) / 5.0e6, "estimated JVM RadixOrder 5.0e6 rows/sec",
          sort_seconds=round(dt, 2))
    rf = h2o3_tpu.Frame.from_numpy({
        "k": r.randint(0, n // 2, n // 4).astype(float),
        "rv": np.arange(n // 4, dtype=float)})
    _device_merge(fr, rf, "inner")                 # warmup/compile
    t0 = time.time()
    m = _device_merge(fr, rf, "inner")
    dt = time.time() - t0
    _emit(f"Merge inner {n/1e6:.0f}M x {n/4e6:.1f}M rows (device join)",
          n / dt, "rows/sec/chip",
          (n / dt) / 3.0e6, "estimated JVM BinaryMerge 3.0e6 rows/sec",
          merge_seconds=round(dt, 2), out_rows=m.nrows)


def bench_cloud():
    """Cloud control plane (ISSUE 7): shutdown → init reformation cost
    plus heartbeat agreement round-trip over the live mesh — the two
    latencies a multi-host pod pays at bootstrap and once per interval
    for the life of the cloud."""
    import h2o3_tpu
    from h2o3_tpu.core import heartbeat
    t0 = time.time()
    h2o3_tpu.shutdown()
    h2o3_tpu.init()
    boot_s = time.time() - t0
    heartbeat.monitor.start(interval_s=3600, thread=False)  # manual rounds
    assert heartbeat.monitor.round()          # warmup/compile
    reps = 50
    t0 = time.time()
    for _ in range(reps):
        assert heartbeat.monitor.round()
    rtt = (time.time() - t0) / reps
    heartbeat.monitor.stop()
    _emit("cloud bootstrap + heartbeat agreement round-trip",
          1.0 / rtt, "rounds/sec", 1.0,
          "H2O HeartBeatThread 1 round/sec/node",
          bootstrap_s=round(boot_s, 3),
          heartbeat_rtt_ms=round(rtt * 1e3, 3))


def bench_automl():
    from h2o3_tpu.automl import H2OAutoML
    from h2o3_tpu.io.stream import stream_import_csv
    n_rows = 200_000 if FAST else 500_000
    fr = stream_import_csv(_airlines_csv(n_rows))
    # hard wallclock bound: AutoML must never outlive the bench budget
    # (round 2's unbounded 20-model 3-fold run ate the driver window)
    cap = max(120.0, min(420.0, _remaining() - 120.0))
    t0 = time.time()
    aml = H2OAutoML(max_models=20, seed=1, nfolds=3, max_runtime_secs=cap)
    aml.train(y="IsDepDelayed", training_frame=fr)
    dt = time.time() - t0
    tab = aml.leaderboard.as_table()
    best_auc = None
    try:
        best_auc = round(float(tab[0].get("auc")), 4)
    except Exception:
        pass
    est_ref = 300.0   # estimated JVM wallclock, same 500K-row config
    planned = 20
    extra = {}
    if len(tab) < planned // 2:
        # LOUD shortfall flag (VERDICT r4 weak #10): a 3-of-20 run must
        # not hide inside a green rc=0
        extra["SHORTFALL"] = f"trained {len(tab)}/{planned} planned"
    _emit(
        f"AutoML max_models=20 airlines {n_rows/1e3:.0f}K wallclock",
        dt, "seconds",
        est_ref / dt, "estimated JVM 300s same config",
        n_models=len(tab), planned_models=planned, best_auc=best_auc,
        max_runtime_secs=round(cap, 0), **extra)


def bench_grid():
    """Model-batched grid search (parallel/model_batch.py): one
    numeric-only GBM shape bucket trained as a single vmapped program
    vs the sequential per-combo walk — models/sec, both paths."""
    import h2o3_tpu
    from h2o3_tpu.ml.grid import GridSearch
    from h2o3_tpu.models.gbm import GBMEstimator
    n = 100_000 if FAST else 500_000
    r = np.random.RandomState(9)
    X = r.randn(n, 6).astype(np.float32)
    yv = (X[:, 0] + 0.5 * X[:, 1] + 0.5 * r.randn(n) > 0).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(6)}
    cols["y"] = np.array(["N", "Y"], object)[yv]
    fr = h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])
    hyper = {"learn_rate": [0.05, 0.08, 0.1, 0.15],
             "sample_rate": [0.7, 1.0],
             "min_rows": [5.0, 20.0]}            # 16 combos, ONE bucket
    n_combos = 4 * 2 * 2
    fixed = dict(ntrees=20, max_depth=6, seed=1)

    def _run(batch_mode):
        os.environ["H2O3TPU_BATCH_MODELS"] = batch_mode
        try:
            t0 = time.time()
            g = GridSearch(GBMEstimator, hyper, **fixed).train(fr, y="y")
            return time.time() - t0, g
        finally:
            os.environ.pop("H2O3TPU_BATCH_MODELS", None)

    # warmup compiles both programs on a 2-combo slice
    wf = dict(fixed)
    whyper = {"learn_rate": [0.05, 0.1]}
    for mode in ("auto", "off"):
        os.environ["H2O3TPU_BATCH_MODELS"] = mode
        GridSearch(GBMEstimator, whyper, **wf).train(fr, y="y")
    os.environ.pop("H2O3TPU_BATCH_MODELS", None)
    c0 = _compile_count()
    t_bat, g_bat = _run("auto")
    compiles_bat = _compile_count() - c0
    t_seq, _ = _run("off")
    mps_bat = n_combos / t_bat
    mps_seq = n_combos / t_seq
    _emit(
        f"grid GBM {n_combos} combos {n/1e3:.0f}K rows "
        f"(model-batched vmap vs sequential walk)",
        mps_bat, "models/sec",
        mps_bat / mps_seq, "sequential per-combo walk, same config",
        batched_seconds=round(t_bat, 1),
        sequential_seconds=round(t_seq, 1),
        n_models=len(g_bat.models),
        compiles_timed=compiles_bat,
        peak_hbm_gb=round(_hbm_peak() / 1e9, 2))


def bench_sched():
    """Cluster work scheduler (ISSUE 15, parallel/scheduler.py): the
    same 16-combo GBM grid through the scheduled path — items planned,
    leased, models detached, lowered to device-independent bytes and
    reinstalled (the exact cross-host contract) — vs the
    coordinator-only walk. On the single-process bench cloud the
    scheduled run degrades to the inline executor, so this line prices
    the scheduling + serialization tax every distributed run pays; the
    model counts must match exactly (the bit-parity contract's cheap
    proxy here, asserted in full by the multiprocess tier-1 test)."""
    import h2o3_tpu
    from h2o3_tpu.core import config as _cfg
    from h2o3_tpu.ml.grid import GridSearch
    from h2o3_tpu.models.gbm import GBMEstimator
    from h2o3_tpu.parallel import scheduler
    n = 50_000 if FAST else 200_000
    r = np.random.RandomState(23)
    X = r.randn(n, 6).astype(np.float32)
    yv = (X[:, 0] - 0.5 * X[:, 2] + 0.5 * r.randn(n) > 0).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(6)}
    cols["y"] = np.array(["N", "Y"], object)[yv]
    fr = h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])
    hyper = {"learn_rate": [0.05, 0.08, 0.1, 0.15],
             "sample_rate": [0.7, 1.0],
             "min_rows": [5.0, 20.0]}            # 16 combos
    n_combos = 4 * 2 * 2
    fixed = dict(ntrees=10, max_depth=5, seed=7)

    def _run(sched_mode):
        prev = _cfg.ARGS.scheduler
        _cfg.ARGS.scheduler = sched_mode
        try:
            t0 = time.time()
            g = GridSearch(GBMEstimator, hyper, **fixed).train(fr, y="y")
            return time.time() - t0, g
        finally:
            _cfg.ARGS.scheduler = prev

    # warmup compiles both paths on a 2-combo slice
    whyper = {"learn_rate": [0.05, 0.1]}
    prev = _cfg.ARGS.scheduler
    for m in ("on", "off"):
        _cfg.ARGS.scheduler = m
        try:
            GridSearch(GBMEstimator, whyper, **fixed).train(fr, y="y")
        finally:
            _cfg.ARGS.scheduler = prev
    s0 = scheduler.snapshot()
    t_on, g_on = _run("on")
    s1 = scheduler.snapshot()
    t_off, g_off = _run("off")
    assert len(g_on.models) == len(g_off.models) == n_combos
    assert s1["runs"] == s0["runs"] + 1, (s0, s1)
    mps_on = n_combos / t_on
    mps_off = n_combos / t_off
    _emit(
        f"sched GBM {n_combos} combos {n/1e3:.0f}K rows "
        f"(scheduled lease/detach/install path vs coordinator-only walk)",
        mps_on, "models/sec",
        mps_on / mps_off, "coordinator-only walk, same config",
        scheduled_seconds=round(t_on, 1),
        coordinator_seconds=round(t_off, 1),
        sched_items=s1["items_done"] - s0["items_done"],
        n_models=len(g_on.models),
        leases_held_now=scheduler.leases_held())


def bench_treekernel():
    """Kernel-level histogram+split+partition throughput
    (rows·features/sec), fused Pallas level pass vs the XLA composition
    on identical shapes — the ISSUE 6 microbench behind the flagship
    GBM number. Native Pallas on TPU; on other backends the kernels run
    through the interpreter at a token size (the line then measures the
    interpreter, and says so)."""
    import jax
    import jax.numpy as jnp
    from h2o3_tpu.frame.binning import BinnedMatrix
    from h2o3_tpu.models.tree import TreeScalars
    from h2o3_tpu.ops.pallas import treekernel as tk
    from h2o3_tpu.parallel.mesh import (get_mesh, padded_rows,
                                        put_sharded, row_sharding)

    native = jax.default_backend() == "tpu"
    n = (1 << 23 if not FAST else 1 << 21) if native else 1 << 14
    F, B, L, d, block_rows = 10, 65, 8, 3, 4096
    n = padded_rows(n)
    r = np.random.RandomState(13)
    mesh = get_mesh()
    bm = BinnedMatrix(
        bins=put_sharded(jnp.asarray(r.randint(0, B, (n, F)).astype(np.int8)),
                         row_sharding()),
        nbins=jnp.full((F,), B - 1, jnp.int32),
        edges=jnp.zeros((F, B - 2), jnp.float32),
        is_cat=np.zeros((F,), bool), names=[f"x{i}" for i in range(F)],
        nbins_total=B, nrows=n, domains=[None] * F)
    tiles = bm.tile_view(block_rows)           # bin-major tile layout
    bins = tiles.bins
    nid = put_sharded(jnp.asarray(r.randint(0, L, n).astype(np.int32)),
                      row_sharding())
    w = jnp.asarray((r.rand(n) > 0.05).astype(np.float32))
    g = jnp.asarray(r.randn(n).astype(np.float32))
    h = jnp.asarray(r.rand(n).astype(np.float32))
    stats = jnp.stack([w, w * g, w * h], axis=1).astype(jnp.float32)
    # any nonneg prev histogram exercises the sibling-subtract path;
    # throughput does not care that it is synthetic
    prev = jnp.asarray(
        np.abs(r.randn(L // 2, F, B, 3)).astype(np.float32)) * 8.0
    cm = jnp.ones((F,), bool)
    nb = bm.nbins
    lo = jnp.full((1,), -jnp.inf, jnp.float32)
    hi = jnp.full((1,), jnp.inf, jnp.float32)
    sc = TreeScalars(jnp.float32(10.0), jnp.float32(1.0),
                     jnp.float32(1e-5), jnp.int32(30))
    kw = dict(d=d, n_nodes=L, n_bins=B, block_rows=block_rows, mesh=mesh)

    def run_pallas(bins, nid, stats, prev):
        out = tk.fused_level(bins, nid, stats, prev, cm, nb, None, None,
                             lo, hi, sc, interpret=not native, **kw)
        return out[1], out[-1]          # gains + routed ids force all

    def run_xla(bins, nid, prev):
        out = tk.xla_level(bins, nid, w, g, h, prev, cm, nb, None, None,
                           lo, hi, sc, **kw)
        return out[1], out[-1]

    jp = jax.jit(run_pallas)
    jx = jax.jit(run_xla)
    for f in jax.block_until_ready(jp(bins, nid, stats, prev)):
        pass                            # warmup/compile
    jax.block_until_ready(jx(bins, nid, prev))
    reps = 10 if native else 3
    c0 = _compile_count()
    t0 = time.time()
    for _ in range(reps):
        out = jp(bins, nid, stats, prev)
    jax.block_until_ready(out)
    t_pallas = (time.time() - t0) / reps
    t0 = time.time()
    for _ in range(reps):
        out = jx(bins, nid, prev)
    jax.block_until_ready(out)
    t_xla = (time.time() - t0) / reps
    rate_p = n * F / t_pallas
    rate_x = n * F / t_xla
    _emit(
        f"treekernel fused hist+split+partition level d={d} "
        f"{n/1e6:.1f}M rows x {F}F x {B}B "
        f"({'native Pallas' if native else 'Pallas interpreter'})",
        rate_p, "rows-feat/sec/chip",
        rate_p / rate_x, "XLA histogram+scan+route, same shapes/mesh",
        xla_rows_feat_per_sec=round(rate_x, 1),
        pallas_level_ms=round(t_pallas * 1e3, 2),
        xla_level_ms=round(t_xla * 1e3, 2),
        tile_rows=tiles.rows, tiles=tiles.ntiles,
        mode="native" if native else "interpret",
        compiles_timed=_compile_count() - c0,
        peak_hbm_gb=round(_hbm_peak() / 1e9, 2))


def bench_checkpoint():
    """In-fit checkpoint overhead (ISSUE 9): the SAME GBM fit with and
    without FitCheckpointer snapshotting at the default 25-tree cadence
    — the overhead %% is the acceptance number (<= 2%% of fit wall time
    on the flagship config; core/recovery.py)."""
    import tempfile

    import h2o3_tpu
    from h2o3_tpu import telemetry
    from h2o3_tpu.core import recovery
    from h2o3_tpu.models.gbm import GBMEstimator
    n = 200_000 if FAST else 1_000_000
    r = np.random.RandomState(11)
    X = r.randn(n, 8).astype(np.float32)
    yv = (X[:, 0] + 0.5 * X[:, 1] + 0.5 * r.randn(n) > 0).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(8)}
    cols["y"] = np.array(["N", "Y"], object)[yv]
    fr = h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])
    del X
    kw = dict(ntrees=100, max_depth=6, seed=1)
    wm = GBMEstimator(**{**kw, "ntrees": 25}).train(fr, y="y")  # warmup
    from h2o3_tpu.core.kv import DKV
    DKV.remove(wm.key)
    t0 = time.time()
    GBMEstimator(**kw).train(fr, y="y")
    t_plain = time.time() - t0
    d = tempfile.mkdtemp(prefix="h2o3tpu_bench_ckpt_")
    w0 = telemetry.REGISTRY.total("fit_checkpoints_written_total")
    with recovery.fit_checkpoint_scope(d):
        t0 = time.time()
        GBMEstimator(**kw).train(fr, y="y")
        t_ckpt = time.time() - t0
    writes = int(telemetry.REGISTRY.total("fit_checkpoints_written_total")
                 - w0)
    overhead_pct = 100.0 * (t_ckpt - t_plain) / max(t_plain, 1e-9)
    _emit(
        f"checkpoint GBM-100trees-d6 {n/1e3:.0f}K rows (in-fit "
        f"snapshotting every 25 trees vs none)",
        overhead_pct, "overhead_pct",
        t_plain / max(t_ckpt, 1e-9), "same fit without checkpointing",
        plain_seconds=round(t_plain, 2),
        checkpointed_seconds=round(t_ckpt, 2),
        snapshots_written=writes,
        peak_hbm_gb=round(_hbm_peak() / 1e9, 2))


def bench_memgov():
    """HBM governor overhead (ISSUE 11): the SAME GBM fit with an
    unlimited budget vs a tight ``H2O3TPU_HBM_BUDGET_MB`` that forces
    the admission path to spill cold frames before dispatch — the
    overhead %% plus the spill/restore counts are the scoreboard
    numbers (core/memgov.py)."""
    import h2o3_tpu
    from h2o3_tpu import telemetry
    from h2o3_tpu.core import memgov
    from h2o3_tpu.core.cleaner import _frame_nbytes
    from h2o3_tpu.core.kv import DKV
    from h2o3_tpu.models.gbm import GBMEstimator
    n = 100_000 if FAST else 500_000
    r = np.random.RandomState(13)
    X = r.randn(n, 8).astype(np.float32)
    yv = (X[:, 0] + 0.5 * X[:, 1] + 0.5 * r.randn(n) > 0).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(8)}
    cols["y"] = np.array(["N", "Y"], object)[yv]
    fr = h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])
    # cold residents for the governor to spill ahead of the fit
    decoys = [h2o3_tpu.Frame.from_numpy(
        {f"d{i}": r.randn(n).astype(np.float32) for i in range(8)})
        for _ in range(3)]
    del X
    kw = dict(ntrees=50, max_depth=6, seed=1)
    feats = [f"x{i}" for i in range(8)]
    wm = GBMEstimator(**{**kw, "ntrees": 10}).train(fr, y="y")  # warmup
    DKV.remove(wm.key)
    t0 = time.time()
    GBMEstimator(**kw).train(fr, y="y")
    t_plain = time.time() - t0
    s0 = telemetry.REGISTRY.total("frame_spills_total")
    r0 = telemetry.REGISTRY.total("frame_restores_total")
    # budget sized so the fit admits only after ~half the decoy bytes
    # spill: resident + projected > budget > (resident - decoys) +
    # projected
    proj = memgov.estimate_fit_bytes("gbm", kw, fr, feats)
    decoy_bytes = sum(_frame_nbytes(d) for d in decoys)
    budget = memgov.governor.resident_bytes() - decoy_bytes // 2 + proj
    os.environ["H2O3TPU_HBM_BUDGET_MB"] = str(max(budget >> 20, 1))
    try:
        t0 = time.time()
        GBMEstimator(**kw).train(fr, y="y")
        t_tight = time.time() - t0
        DKV.get(decoys[0].key)      # touch a spilled decoy: restore
    finally:
        os.environ.pop("H2O3TPU_HBM_BUDGET_MB", None)
    spills = int(telemetry.REGISTRY.total("frame_spills_total") - s0)
    restores = int(telemetry.REGISTRY.total("frame_restores_total") - r0)
    overhead_pct = 100.0 * (t_tight - t_plain) / max(t_plain, 1e-9)
    _emit(
        f"memgov GBM-50trees-d6 {n/1e3:.0f}K rows (tight HBM budget "
        f"with admission spills vs unlimited)",
        overhead_pct, "overhead_pct",
        t_plain / max(t_tight, 1e-9), "same fit, unlimited budget",
        plain_seconds=round(t_plain, 2),
        tight_seconds=round(t_tight, 2),
        budget_mb=max(budget >> 20, 1),
        spills=spills, restores=restores,
        peak_hbm_gb=round(_hbm_peak() / 1e9, 2))


def bench_ingest():
    """Chunk-parallel ingest pipeline (ISSUE 12): airlines-CSV MB/s with
    the tokenizer fan-out vs the SAME pipeline pinned to one worker
    (bit-identical output by construction — tests/test_ingest_parallel
    asserts the bits, this config measures the ratio), plus the
    row-group-parallel Parquet fast path over the same rows."""
    from h2o3_tpu.core.kv import DKV
    from h2o3_tpu.io.chunking import resolve_workers
    from h2o3_tpu.io.formats import parse_parquet
    from h2o3_tpu.io.stream import stream_import_csv
    n = 1_000_000 if FAST else 10_000_000
    path = _airlines_csv(n)
    nbytes = os.path.getsize(path)

    def _run(workers):
        fr = stream_import_csv(path, workers=workers)
        rows = fr.nrows
        DKV.remove(fr.key)
        return rows

    _run(1)                                 # warmup/compile both legs
    t0 = time.time()
    rows = _run(1)
    t_seq = max(time.time() - t0, 1e-9)
    t0 = time.time()
    _run(None)
    t_par = max(time.time() - t0, 1e-9)
    w = resolve_workers()
    _emit(f"Ingest airlines CSV {n/1e6:.0f}M rows x{w} workers "
          f"(chunk-parallel tokenize + overlapped transfer)",
          nbytes / t_par / 1e6, "MB/sec",
          t_seq / t_par, "same pipeline, workers=1",
          seq_mb_per_s=round(nbytes / t_seq / 1e6, 1),
          workers=w, rows=rows, file_mb=round(nbytes / 1e6, 1),
          seq_seconds=round(t_seq, 2), par_seconds=round(t_par, 2))
    # Parquet leg: same rows through the arrow-columnar fast path (no
    # CSV tokenizer at all) — baseline is the sequential CSV wall time
    import pyarrow.csv as pacsv
    import pyarrow.parquet as pq
    ppath = path.rsplit(".", 1)[0] + ".parquet"
    if not os.path.exists(ppath):
        pq.write_table(pacsv.read_csv(path), ppath + ".tmp",
                       row_group_size=1 << 20)
        os.rename(ppath + ".tmp", ppath)
    pbytes = os.path.getsize(ppath)
    DKV.remove(parse_parquet(ppath).key)    # warmup
    t0 = time.time()
    fr = parse_parquet(ppath)
    t_pq = max(time.time() - t0, 1e-9)
    DKV.remove(fr.key)
    _emit(f"Ingest airlines Parquet {n/1e6:.0f}M rows "
          f"(row-group-parallel arrow fast path)",
          pbytes / t_pq / 1e6, "MB/sec",
          t_seq / t_pq, "same rows, sequential CSV",
          parquet_seconds=round(t_pq, 2),
          file_mb=round(pbytes / 1e6, 1), workers=w)


def bench_serving():
    """Low-latency scoring tier (ISSUE 14): row-payload predict QPS and
    tail latency through the continuous micro-batcher vs the SAME
    requests scored one at a time through ``Model.predict``. Outputs
    are bit-identical by construction — the serving engine dispatches
    the model's own compiled program (models/model.py _serve_jit) — so
    this config measures throughput/latency only, plus the compile
    observer's per-bucket miss counts (a compile storm here means the
    row buckets are broken)."""
    import threading

    import h2o3_tpu
    from h2o3_tpu import telemetry
    from h2o3_tpu.core.kv import DKV
    from h2o3_tpu.models.gbm import GBMEstimator
    from h2o3_tpu.serving.engine import engine
    from h2o3_tpu.serving.rows import parse_rows, serving_schema

    n = 20_000 if FAST else 100_000
    r = np.random.RandomState(14)
    X = r.randn(n, 8).astype(np.float32)
    yv = (X[:, 0] + 0.5 * X[:, 1] + 0.5 * r.randn(n) > 0).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(8)}
    cols["y"] = np.array(["N", "Y"], object)[yv]
    fr = h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])
    model = GBMEstimator(ntrees=20, max_depth=5, seed=1).train(fr, y="y")

    n_clients = 16
    reqs_per_client = 25 if FAST else 50
    rows_per_req = 8
    feats = [f"x{i}" for i in range(8)]
    rr = np.random.RandomState(15)
    payloads = []
    for _ in range(n_clients * reqs_per_client):
        vals = rr.randn(rows_per_req, len(feats))
        payloads.append([
            {f: float(vals[i, j]) for j, f in enumerate(feats)}
            for i in range(rows_per_req)])

    # sequential baseline: what a naive per-request server does —
    # parse rows, build a frame, Model.predict, fetch (warmed first so
    # neither leg pays XLA compiles inside the timed window)
    schema = serving_schema(model)

    def _predict_once(rows):
        parsed = parse_rows(schema, rows)
        pf = h2o3_tpu.Frame.from_numpy(
            parsed, domains={nm: d for nm, d in schema if d is not None})
        DKV.remove(pf.key)
        try:
            out = model.predict(pf)
            DKV.remove(out.key)
        finally:
            pf.drop_device_caches()

    _predict_once(payloads[0])                   # warm the per-request shape
    engine.register(model)                       # warm the serving tier
    n_seq = min(len(payloads), 40 if FAST else 80)
    t0 = time.time()
    for rows in payloads[:n_seq]:
        _predict_once(rows)
    t_seq = max(time.time() - t0, 1e-9)
    qps_seq = n_seq / t_seq

    # concurrent leg: n_clients threads hammer engine.score_rows; the
    # micro-batcher coalesces whatever overlaps into one padded dispatch
    lat = []
    lat_lock = threading.Lock()
    errors = []

    def _client(cid):
        mine = payloads[cid * reqs_per_client:(cid + 1) * reqs_per_client]
        for rows in mine:
            t = time.time()
            try:
                engine.score_rows(model, rows)
            except BaseException as e:   # noqa: BLE001 - scoreboard, not crash
                errors.append(e)
                return
            with lat_lock:
                lat.append(time.time() - t)

    # untimed warm burst: compiles the coalesced row buckets so the
    # timed window measures steady-state serving, not first-compile
    warm_threads = [threading.Thread(
        target=lambda: engine.score_rows(model, payloads[0]))
        for _ in range(n_clients)]
    for t in warm_threads:
        t.start()
    for t in warm_threads:
        t.join()
    lat.clear()

    d0 = engine._batchers[model.key].dispatches
    t0 = time.time()
    threads = [threading.Thread(target=_client, args=(c,))
               for c in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    t_conc = max(time.time() - t0, 1e-9)
    assert not errors, errors[0]
    qps = len(lat) / t_conc
    lat_ms = sorted(v * 1e3 for v in lat)
    p50 = lat_ms[len(lat_ms) // 2]
    p99 = lat_ms[min(len(lat_ms) - 1, int(len(lat_ms) * 0.99))]
    dispatches = engine._batchers[model.key].dispatches - d0

    # compile accounting: every serving compile must map to a distinct
    # row bucket — more misses than buckets means the cache is broken
    with telemetry.REGISTRY._lock:
        miss_sigs = [labels for (nm, _), m in
                     telemetry.REGISTRY._metrics.items()
                     for labels in [getattr(m, "labels", {})]
                     if nm.endswith("jit_cache_miss_total")
                     and labels.get("fn") == "serving.gbm" and m.value > 0]
    buckets = len(engine._scorers[model.key].buckets)
    assert len(miss_sigs) <= max(buckets, 1), (miss_sigs, buckets)

    _emit(f"serving GBM row-payload predict {n_clients} clients x "
          f"{reqs_per_client} reqs x {rows_per_req} rows "
          f"(continuous micro-batch vs sequential Model.predict)",
          qps, "requests/sec", qps / qps_seq,
          "same requests, sequential Model.predict",
          sequential_qps=round(qps_seq, 1),
          p50_ms=round(p50, 2), p99_ms=round(p99, 2),
          requests=len(lat), dispatches=dispatches,
          mean_batch_width=round(len(lat) / max(dispatches, 1), 2),
          row_buckets=buckets,
          serving_compiles=len(miss_sigs),
          scorer_cache_hits=int(telemetry.REGISTRY.total(
              "scorer_cache_hits_total")),
          scorer_cache_misses=int(telemetry.REGISTRY.total(
              "scorer_cache_misses_total")))


def bench_tracing():
    """Distributed-tracing overhead (ISSUE 16): the SAME GBM fit with
    and without a trace context installed (the REST ingress condition —
    every span additionally stamps/propagates the request's trace id;
    telemetry/trace_context.py). The overhead %% is the acceptance
    number (< 2%% of fit wall time)."""
    import h2o3_tpu
    from h2o3_tpu import telemetry
    from h2o3_tpu.core.kv import DKV
    from h2o3_tpu.models.gbm import GBMEstimator
    from h2o3_tpu.telemetry import trace_context
    n = 200_000 if FAST else 1_000_000
    r = np.random.RandomState(16)
    X = r.randn(n, 8).astype(np.float32)
    yv = (X[:, 0] + 0.5 * X[:, 1] + 0.5 * r.randn(n) > 0).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(8)}
    cols["y"] = np.array(["N", "Y"], object)[yv]
    fr = h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])
    del X
    kw = dict(ntrees=100, max_depth=6, seed=1)
    wm = GBMEstimator(**{**kw, "ntrees": 25}).train(fr, y="y")  # warmup
    DKV.remove(wm.key)
    t0 = time.time()
    GBMEstimator(**kw).train(fr, y="y")
    t_plain = time.time() - t0
    with trace_context.trace_scope(trace_context.new_context()), \
            telemetry.span("rest", route="/99/bench"):
        t0 = time.time()
        m = GBMEstimator(**kw).train(fr, y="y")
        t_traced = time.time() - t0
    # every span of the traced fit carries the request's trace id
    stamped = sum(1 for s in telemetry.spans_snapshot(2048)
                  if s.get("trace_id"))
    assert stamped > 0, "traced fit produced no trace-stamped spans"
    DKV.remove(m.key)
    overhead_pct = 100.0 * (t_traced - t_plain) / max(t_plain, 1e-9)
    assert overhead_pct < 2.0, \
        f"tracing overhead {overhead_pct:.2f}% >= 2% acceptance bound"
    _emit(
        f"tracing GBM-100trees-d6 {n/1e3:.0f}K rows (trace context "
        f"installed + ingress span vs bare fit)",
        overhead_pct, "overhead_pct",
        t_plain / max(t_traced, 1e-9), "same fit without tracing",
        plain_seconds=round(t_plain, 2),
        traced_seconds=round(t_traced, 2),
        trace_stamped_spans=stamped,
        peak_hbm_gb=round(_hbm_peak() / 1e9, 2))


_FLEET_WORKER_SRC = '''
"""bench fleet worker: one pod process (generated by bench.py)."""
import json, os, signal, sys, threading, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["H2O3TPU_HEARTBEAT_INTERVAL_S"] = "0.25"
os.environ["H2O3TPU_FLEET_LOAD_TTL_S"] = "0.2"
sys.path.insert(0, os.environ["H2O3TPU_BENCH_REPO"])
coord, nproc, pid, outfile = sys.argv[1:5]
nproc, pid = int(nproc), int(pid)
import jax
jax.config.update("jax_default_device", None)
import h2o3_tpu
h2o3_tpu.init(backend="cpu", coordinator_address=coord,
              num_processes=nproc, process_id=pid)
import numpy as np
from h2o3_tpu.core.kv import DKV
from h2o3_tpu.serving import fleet

r = np.random.RandomState(31)
n = 1500
fr = h2o3_tpu.Frame.from_numpy(
    {"a": r.randn(n), "b": r.randn(n),
     "y": r.randn(n) + 0.5})
from h2o3_tpu.models.gbm import GBMEstimator
model = GBMEstimator(ntrees=3, max_depth=3, seed=9).train(fr, y="y")
MKEY = str(model.key)
ROWS = [{"a": float(i) * 0.1, "b": 1.0 - float(i) * 0.05}
        for i in range(8)]
from h2o3_tpu.api.server import start_server
port = start_server(port=0, background=True)
killflag = outfile + ".killflag"

# publish is an SPMD point on a live cloud (the lowering pickle
# allgathers cross-process sharded arrays): both processes call it here
fleet.publish(model)

if pid == 1:
    DKV.remove(MKEY)
    fleet.install_published(MKEY)
    while not os.path.exists(killflag):
        time.sleep(0.05)
    os.kill(os.getpid(), signal.SIGKILL)

DKV.remove(MKEY)
deadline = time.monotonic() + 60
while not (1 in fleet.replicas(MKEY) and 1 in fleet.endpoints()):
    if time.monotonic() > deadline:
        raise RuntimeError("replica never registered")
    time.sleep(0.05)

import urllib.request


def predict_once(timeout=20.0):
    req = urllib.request.Request(
        "http://127.0.0.1:%d/3/Predictions/models/%s" % (port, MKEY),
        data=json.dumps({"rows": ROWS}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    t = time.monotonic()
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        out = json.loads(resp.read())
    return time.monotonic() - t, out["predictions"]["predict"]


def drive(n_req, clients):
    lats, preds, lock = [], [], threading.Lock()

    def one():
        lat, p = predict_once()
        with lock:
            lats.append(lat)
            preds.append(p)

    t0 = time.monotonic()
    for lo in range(0, n_req, clients):
        ts = [threading.Thread(target=one)
              for _ in range(min(clients, n_req - lo))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    wall = max(time.monotonic() - t0, 1e-9)
    lats.sort()
    return {"qps": len(lats) / wall,
            "p99_ms": lats[min(len(lats) - 1,
                               int(len(lats) * 0.99))] * 1e3,
            "pred": preds[0]}


n_req = int(os.environ.get("H2O3TPU_BENCH_FLEET_REQS", "30"))
predict_once()                                   # warm the route
routed = {c: drive(n_req, c) for c in (1, 4)}

with open(killflag, "w") as f:
    f.write("die")
t0 = time.monotonic()
recovery_s, pred_after = None, None
while time.monotonic() - t0 < 90:
    try:
        _lat, pred_after = predict_once()
        recovery_s = time.monotonic() - t0
        break
    except Exception:
        time.sleep(0.05)

local = {c: drive(n_req, c) for c in (1, 4)}

with open(outfile + ".0", "w") as f:
    json.dump({"routed": routed, "local": local,
               "recovery_s": recovery_s, "pred_after": pred_after,
               "installed": MKEY in fleet.stats()["local_replicas"]},
              f)
print("FLEET-BENCH-0-DONE", flush=True)
os._exit(0)
'''


def bench_fleet():
    """Fleet serving resilience (ISSUE 17, serving/fleet.py): a REAL
    2-process CPU cloud — one replica node, one routing-only node. The
    router node's REST edge answers row-payload predicts by proxying to
    the replica (routed leg), then the replica is SIGKILLed and the line
    prices the RECOVERY: hedged failover installs the published binary
    locally and the first successful answer stamps recovery_seconds.
    The local leg (post-recovery) is the single-node baseline — routed
    p99 carries one 127.0.0.1 HTTP hop over it, and the answers must
    match exactly (the bit-parity contract's cheap proxy here; asserted
    in full by tests/test_fleet.py)."""
    import socket
    import subprocess
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        worker = os.path.join(tmp, "fleet_bench_worker.py")
        with open(worker, "w") as f:
            f.write(_FLEET_WORKER_SRC)
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
        s.close()
        out = os.path.join(tmp, "fleet.json")
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env["H2O3TPU_BENCH_REPO"] = os.path.dirname(
            os.path.abspath(__file__))
        env["H2O3TPU_BENCH_FLEET_REQS"] = "20" if FAST else "40"
        procs = [subprocess.Popen(
            [sys.executable, worker, coord, "2", str(i), out],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT) for i in range(2)]
        deadline = time.time() + 420
        for p in procs:
            try:
                p.wait(timeout=max(deadline - time.time(), 1.0))
            except subprocess.TimeoutExpired:
                for q in procs:
                    if q.poll() is None:
                        q.kill()
        assert procs[0].returncode == 0, "fleet driver process failed"
        with open(out + ".0") as f:
            res = json.load(f)

    assert res["recovery_s"] is not None, "never recovered from kill"
    assert res["installed"], "failover never installed the binary"
    # bit-parity proxy: routed, post-kill, and local answers identical
    assert (res["routed"][  "4"]["pred"] == res["local"]["4"]["pred"]
            == res["pred_after"])
    qps_r1, qps_r4 = res["routed"]["1"]["qps"], res["routed"]["4"]["qps"]
    qps_l4 = res["local"]["4"]["qps"]
    _emit(
        "fleet routed row-payload predict, 2-process cloud "
        "(proxy to replica; SIGKILL replica -> hedged local install)",
        qps_r4, "requests/sec",
        qps_r4 / max(qps_l4, 1e-9), "same predicts served locally "
        "(single node, post-recovery)",
        routed_qps_1client=round(qps_r1, 1),
        routed_qps_4clients=round(qps_r4, 1),
        client_scaling=round(qps_r4 / max(qps_r1, 1e-9), 2),
        routed_p99_ms=round(res["routed"]["4"]["p99_ms"], 2),
        local_p99_ms=round(res["local"]["4"]["p99_ms"], 2),
        local_qps_4clients=round(qps_l4, 1),
        kill_recovery_seconds=round(res["recovery_s"], 3))


_DUR_WORKER_SRC = '''
"""bench durability worker: one pod process (generated by bench.py)."""
import json, os, signal, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["H2O3TPU_HEARTBEAT_INTERVAL_S"] = "0.25"
os.environ["H2O3TPU_DATA_DURABILITY"] = "mirror"
os.environ["H2O3TPU_DUR_REBUILD_S"] = "0.05"
sys.path.insert(0, os.environ["H2O3TPU_BENCH_REPO"])
coord, nproc, pid, outfile = sys.argv[1:5]
nproc, pid = int(nproc), int(pid)
os.environ["H2O3TPU_DUR_DIR"] = outfile + ".mirror"
import jax
jax.config.update("jax_default_device", None)
import h2o3_tpu
h2o3_tpu.init(backend="cpu", coordinator_address=coord,
              num_processes=nproc, process_id=pid)
import numpy as np
from h2o3_tpu.core import durability
from h2o3_tpu.core.kv import DKV
from h2o3_tpu.parallel import mesh as mesh_mod

killflag = outfile + ".killflag"
if pid == 1:
    # victim: mirror one frame, announce it, wait for the kill order
    with mesh_mod.local_mesh_scope():
        r = np.random.RandomState(7)
        n = 100_000
        fr = h2o3_tpu.Frame.from_numpy(
            {"a": r.randn(n), "b": r.randn(n), "y": r.randn(n)})
    assert fr.key in durability.stats()["mirrored"]
    with open(killflag + ".ready", "w") as f:
        f.write(fr.key)
    while not os.path.exists(killflag):
        time.sleep(0.02)
    os.kill(os.getpid(), signal.SIGKILL)

# pid 0: wait for the victim's mirrored frame, order the kill, and
# time kill -> frame re-homed locally (staleness detection included)
deadline = time.monotonic() + 60
while not os.path.exists(killflag + ".ready"):
    if time.monotonic() > deadline:
        raise RuntimeError("victim never mirrored its frame")
    time.sleep(0.02)
with open(killflag + ".ready") as f:
    fkey = f.read().strip()
nbytes = durability.registry(1)[fkey]["nbytes"]
with open(killflag, "w") as f:
    f.write("die")
t0 = time.monotonic()
rebuilt_s = None
while time.monotonic() - t0 < 90:
    durability.maybe_rebuild()
    if fkey in DKV:
        rebuilt_s = time.monotonic() - t0
        break
    time.sleep(0.02)
from h2o3_tpu import telemetry
with open(outfile + ".0", "w") as f:
    json.dump({"kill_to_rebuild_s": rebuilt_s,
               "rebuilds": telemetry.counter(
                   "frame_rebuilds_total", source="mirror").value,
               "mirror_nbytes": nbytes}, f)
print("DUR-BENCH-0-DONE", flush=True)
os._exit(0)
'''


def bench_durability():
    """Durable data plane (ISSUE 18, core/durability.py): write-through
    mirror overhead on ingest — ``durability=off`` is the zero-overhead
    default (hook sites gate on the raw env knob before importing
    anything) — plus kill-to-rebuild wall time on a REAL 2-process
    cloud: a peer mirrors a frame, is SIGKILLed, and the survivor's
    recovery supervisor re-homes the frame from its mirror."""
    import shutil
    import socket
    import subprocess
    import tempfile

    import h2o3_tpu
    from h2o3_tpu.core import durability
    from h2o3_tpu.core.kv import DKV
    n = 200_000 if FAST else 2_000_000
    r = np.random.RandomState(11)
    cols = {"a": r.randn(n), "b": r.randn(n), "y": r.randn(n)}
    nbytes = sum(v.nbytes for v in cols.values())

    def _ingest():
        fr = h2o3_tpu.Frame.from_numpy(cols)
        DKV.remove(fr.key)

    _ingest()                                # warmup/compile
    t0 = time.time()
    _ingest()
    t_off = max(time.time() - t0, 1e-9)
    dur_dir = tempfile.mkdtemp(prefix="h2o3tpu-bench-mirror-")
    os.environ["H2O3TPU_DATA_DURABILITY"] = "mirror"
    os.environ["H2O3TPU_DUR_DIR"] = dur_dir
    try:
        _ingest()                            # warmup the mirror path
        t0 = time.time()
        _ingest()
        t_mir = max(time.time() - t0, 1e-9)
    finally:
        os.environ.pop("H2O3TPU_DATA_DURABILITY", None)
        os.environ.pop("H2O3TPU_DUR_DIR", None)
        durability.reset()
        shutil.rmtree(dur_dir, ignore_errors=True)
    _emit(f"durability mirror write-through, {n/1e6:.1f}M-row ingest "
          "(blocks persisted + digested + registered per frame)",
          (t_mir / t_off - 1.0) * 100.0, "percent overhead",
          t_mir / t_off, "durability=off (zero-overhead default)",
          off_seconds=round(t_off, 3), mirror_seconds=round(t_mir, 3),
          frame_mb=round(nbytes / 1e6, 1))

    with tempfile.TemporaryDirectory() as tmp:
        worker = os.path.join(tmp, "dur_bench_worker.py")
        with open(worker, "w") as f:
            f.write(_DUR_WORKER_SRC)
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
        s.close()
        out = os.path.join(tmp, "dur.json")
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env["H2O3TPU_BENCH_REPO"] = os.path.dirname(
            os.path.abspath(__file__))
        procs = [subprocess.Popen(
            [sys.executable, worker, coord, "2", str(i), out],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT) for i in range(2)]
        deadline = time.time() + 420
        for p in procs:
            try:
                p.wait(timeout=max(deadline - time.time(), 1.0))
            except subprocess.TimeoutExpired:
                for q in procs:
                    if q.poll() is None:
                        q.kill()
        assert procs[0].returncode == 0, "durability driver failed"
        with open(out + ".0") as f:
            res = json.load(f)

    assert res["kill_to_rebuild_s"] is not None, "never rebuilt"
    assert res["rebuilds"] >= 1, "rebuild not visible in telemetry"
    _emit("durability kill-to-rebuild, 2-process cloud (SIGKILL the "
          "frame's home; survivor re-homes it from the mirror)",
          res["kill_to_rebuild_s"], "seconds", 1.0,
          "includes heartbeat staleness detection",
          mirror_nbytes=res["mirror_nbytes"],
          rebuilds=res["rebuilds"])


def bench_globalfit():
    """Pod-global sharded training (ISSUE 19, H2O3TPU_GLOBAL_FIT): ONE
    GBM fit data-parallel across a REAL 2-process gloo cloud over a
    host-partitioned frame, vs the same fit on 1 host. On this 1-core
    container both processes timeshare one core, so a ratio below 1.0
    measures collective + timeshare overhead, not pod speedup — the
    scoreboard says so. Plus the SIGKILL-mid-fit leg: a peer dies
    inside the global boost loop and the survivor's job must FAIL
    fast, infra-classified, with no RUNNING job leak."""
    import socket
    import subprocess
    import tempfile

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tests", "globalfit_worker.py")

    def _pod(mode, nproc, tmp, extra_env=None):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
        s.close()
        out = os.path.join(tmp, f"{mode}_{nproc}.json")
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.update(extra_env or {})
        procs = [subprocess.Popen(
            [sys.executable, worker, coord, str(nproc), str(i), out, mode],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT) for i in range(nproc)]
        deadline = time.time() + 240
        for p in procs:
            try:
                p.wait(timeout=max(deadline - time.time(), 1.0))
            except subprocess.TimeoutExpired:
                for q in procs:
                    if q.poll() is None:
                        q.kill()
        if mode != "sigkill":
            assert all(p.returncode == 0 for p in procs), \
                f"globalfit {mode} pod failed"
        else:
            # pid 1 SIGKILLs itself by design, but the surviving pid 0
            # must exit cleanly — a crashed/killed survivor would make
            # any report on disk stale, not a valid result
            assert procs[0].returncode == 0, \
                "globalfit sigkill survivor (pid 0) did not exit cleanly"
        assert os.path.exists(out), \
            f"globalfit {mode} pod wrote no report (hung and killed?)"
        with open(out) as f:
            return json.load(f)

    def _host_phases(tmp, mode, nproc):
        """Per-pid step-profiler splits the workers dropped next to the
        report file — the WHY behind the rows/sec ratio (compute vs
        collective wait vs host, per host)."""
        out = {}
        base = os.path.join(tmp, f"{mode}_{nproc}.json")
        for i in range(nproc):
            try:
                with open(f"{base}.phases.{i}") as f:
                    out[str(i)] = json.load(f)
            except Exception:   # noqa: BLE001 - table is best-effort
                pass
        return out

    with tempfile.TemporaryDirectory() as tmp:
        one = _pod("bench", 1, tmp)
        two = _pod("bench", 2, tmp)
        host_phases = _host_phases(tmp, "bench", 2)
        ratio = two["rows_per_sec"] / max(one["rows_per_sec"], 1e-9)
        _emit("globalfit GBM rows/sec, 2-host gloo pod on a host-"
              "partitioned frame (1-core container: both hosts "
              "timeshare one core, so the ratio is overhead, not "
              "speedup)",
              two["rows_per_sec"], "rows/sec", ratio,
              "same fit on 1 host",
              one_host_rows_per_sec=round(one["rows_per_sec"], 1),
              ntrees=two["ntrees"], nrows=two["nrows"],
              host_phases=host_phases)
        # human-readable per-host phase table next to the rows/sec line
        if host_phases:
            print("# globalfit per-host phase breakdown "
                  "(seconds; telemetry/stepprof.py)", flush=True)
            print(f"# {'host':>4} {'compute':>9} {'collective':>11} "
                  f"{'hostprep':>9} {'checkpoint':>10} {'coll%':>6}",
                  flush=True)
            for h in sorted(host_phases):
                ph = host_phases[h].get("phases") or {}
                tot = sum(ph.values()) or 1.0
                print(f"# {h:>4} {ph.get('compute', 0.0):>9.3f} "
                      f"{ph.get('collective', 0.0):>11.3f} "
                      f"{ph.get('host', 0.0):>9.3f} "
                      f"{ph.get('checkpoint', 0.0):>10.3f} "
                      f"{100.0 * ph.get('collective', 0.0) / tot:>5.1f}%",
                      flush=True)

        kill = _pod("sigkill", 2, tmp,
                    {"H2O3TPU_HEARTBEAT_INTERVAL_S": "0.25",
                     "H2O3TPU_HEARTBEAT_MISS_BUDGET": "2"})
        assert kill["job_status"] == "FAILED", kill
        assert kill["infra_classified"], kill
        assert kill["running_leaks"] == [], kill
        _emit("globalfit SIGKILL-mid-fit, 2-host pod (peer dies inside "
              "the global boost loop; survivor's job fails fast, "
              "classified infra, no RUNNING job leak)",
              kill["fail_after_loss_s"], "seconds", 1.0,
              f"heartbeat window {kill['heartbeat_window_s']:.2f}s",
              job_status=kill["job_status"])


CONFIGS = [("gbm", bench_gbm), ("glm", bench_glm), ("dl", bench_dl),
           ("xgb", bench_xgb), ("sort", bench_sort),
           ("grid", bench_grid), ("treekernel", bench_treekernel),
           ("cloud", bench_cloud), ("checkpoint", bench_checkpoint),
           ("memgov", bench_memgov), ("ingest", bench_ingest),
           ("serving", bench_serving), ("sched", bench_sched),
           ("tracing", bench_tracing), ("fleet", bench_fleet),
           ("durability", bench_durability),
           ("globalfit", bench_globalfit),
           ("automl", bench_automl), ("gbm-full", bench_gbm_full)]

# minimum seconds a config plausibly needs; skipped (with a JSON note)
# rather than started when the remaining budget is below it
_MIN_NEED = {"gbm": 60, "glm": 90, "dl": 60, "xgb": 60, "sort": 60,
             "grid": 120, "treekernel": 60, "cloud": 30, "automl": 180,
             "checkpoint": 90, "memgov": 90, "ingest": 90,
             "serving": 60, "sched": 120, "tracing": 90, "fleet": 120,
             "durability": 120, "globalfit": 120, "gbm-full": 600}

# hard per-config wallclock cap (child process killed past it): a
# wedged worker costs one line, never the scoreboard
_HARD_CAP = {"gbm": 900, "glm": 600, "dl": 600, "xgb": 600, "sort": 400,
             "grid": 600, "treekernel": 400, "cloud": 300, "automl": 900,
             "checkpoint": 600, "memgov": 600, "ingest": 600,
             "serving": 600, "sched": 600, "tracing": 600, "fleet": 600,
             "durability": 600, "globalfit": 600, "gbm-full": 1200}


def _stub_ok(name):
    def _fn():
        _emit(f"stub config {name}", 1.0, "units", 1.0, "stub")
    return _fn


def _stub_wedge():
    # a wedged backend: the child accepts work and never finishes
    time.sleep(3600)


def _stub_grid():
    """`grid` models/sec line without a backend: drives the model-batch
    PLANNER (shape buckets, canonical combo keys, the knob) over a
    synthetic numeric-only GBM grid, so the harness exercises the
    batched-path plumbing even where no accelerator exists."""
    from h2o3_tpu.parallel import model_batch
    combos = [{"learn_rate": lr, "sample_rate": sr, "max_depth": d}
              for lr in (0.05, 0.1) for sr in (0.8, 1.0)
              for d in (5, 12)]       # 8 combos, TWO depth buckets
    t0 = time.time()
    buckets = model_batch.plan_buckets("gbm", combos)
    assert len({model_batch.combo_key(c) for c in combos}) == len(combos)
    dt = max(time.time() - t0, 1e-6)
    _emit("grid GBM 8 combos (stub; bucket planner, no backend)",
          len(combos) / dt, "models/sec", 1.0, "stub",
          buckets=len(buckets),
          widths=sorted(b.width for b in buckets),
          batched=model_batch.enabled())


def _stub_cloud():
    """`cloud` line without a backend: drives the heartbeat monitor's
    miss/degrade/recover state machine via fault injection — the
    bootstrap + peer-health plumbing, no jax dispatches (rounds fail at
    the injection hook before touching a device)."""
    from h2o3_tpu.core import heartbeat, watchdog
    mon = heartbeat.HeartbeatMonitor()
    mon.interval_s, mon.miss_budget, mon.timeout_s = 0.01, 2, 5.0
    mon.peers = {0: {"last_seen": time.time(), "healthy": True}}
    watchdog.inject_fault("heartbeat", times=2)
    try:
        t0 = time.time()
        assert mon.round() is False and mon.healthy()
        assert mon.round() is False and not mon.healthy()
        detect_s = time.time() - t0
        # the flag now kills the next chunk, classified infra
        assert watchdog.is_infra_error(
            heartbeat.CloudUnhealthyError(mon.reason() or "down"))
    finally:
        watchdog.clear_faults()
    rounds = mon.rounds
    _emit("cloud heartbeat (stub; miss->degrade state machine, "
          "no backend)", rounds / max(detect_s, 1e-6), "rounds/sec",
          1.0, "stub", miss_budget=mon.miss_budget,
          detect_ms=round(detect_s * 1e3, 3))


def _stub_roofline():
    """`roofline` line without a backend: drives the peak table and the
    analytic per-algo estimators (telemetry/roofline.py) — mfu/hbm_util
    fields flow even where no accelerator exists, so the harness
    exercises the hardware-relative axis plumbing end to end."""
    from h2o3_tpu.telemetry import roofline
    peaks = roofline.peaks_for("TPU v5 lite")
    assert peaks["flops"] > 0 and peaks["hbm_bytes_per_s"] > 0
    est = roofline.analytic_tree_cost(rows=5_000_000, features=10,
                                      trees=100, depth=6, bins=65)
    seconds = 50.0                      # flagship-shaped pretend fit
    mfu = est["flops"] / (seconds * peaks["flops"])
    hbm = est["bytes"] / (seconds * peaks["hbm_bytes_per_s"])
    assert mfu > 0 and hbm > 0
    glm = roofline.analytic_glm_cost(rows=11_000_000, coefs=29,
                                     iterations=8)
    dl = roofline.analytic_dl_cost(1_000_000 * 8.0, [784, 200, 200, 10])
    assert glm["flops"] > 0 and dl["flops"] > 0
    _emit("roofline GBM flagship shape (stub; analytic estimators + "
          "peak table, no backend)", 100 * mfu, "mfu_pct", 1.0, "stub",
          mfu=round(mfu, 6), hbm_util=round(hbm, 6),
          peak_source=peaks["source"])


def _stub_treekernel():
    """`treekernel` line without a backend: drives the Pallas PLANNER —
    the pure knob/backend decision table and the VMEM tile sizing
    (ops/pallas.decide / vmem_tile_rows) — so the harness exercises the
    kernel-layer plumbing even where no accelerator (or no Pallas)
    exists."""
    from h2o3_tpu.ops import pallas as plx
    decisions = {}
    for knob in ("auto", "off", "interpret", "on"):
        for backend in ("tpu", "cpu"):
            mode, reason = plx.decide(knob, backend, 8, True)
            decisions[f"{knob}/{backend}"] = mode + (
                f" ({reason})" if reason else "")
    # unavailable pallas always resolves off, never raises
    assert plx.decide("auto", "tpu", 8, False)[0] == "off"
    rows = plx.vmem_tile_rows(10, 65, 32)
    assert rows % 8 == 0 and rows >= 8
    _emit("treekernel fused level (stub; knob/tile planner, no backend)",
          float(rows), "rows/tile", 1.0, "stub", decisions=decisions)


def _stub_checkpoint():
    """Backend-free FitCheckpointer state machine: snapshot cadence,
    atomic write, load, bit-flip quarantine (ISSUE 9)."""
    import tempfile

    from h2o3_tpu.core.recovery import FitCheckpointer
    d = tempfile.mkdtemp(prefix="h2o3tpu_stub_ckpt_")
    fc = FitCheckpointer(os.path.join(d, "gbm_stub.fitsnap"), "gbm", 5)
    t0 = time.time()
    n_snap = 0
    for unit in range(5, 55, 5):
        if fc.maybe_save(unit, lambda: {"done": unit,
                                        "payload": b"x" * 4096}):
            n_snap += 1
    dt = max(time.time() - t0, 1e-9)
    loaded = fc.load()
    assert loaded is not None and loaded[0] == 50, loaded
    with open(fc.path, "r+b") as f:       # bit-flip → quarantine
        f.seek(2)
        f.write(b"\xff\xff")
    assert fc.load() is None
    assert any(fn.endswith(".corrupt") for fn in os.listdir(d))
    fc.clear()
    _emit("checkpoint FitCheckpointer (stub; snapshot/load/quarantine "
          "state machine, no backend)", n_snap / dt, "snapshots/sec",
          1.0, "stub", snapshots=n_snap, quarantined=1)


def _stub_memgov():
    """Backend-free memory-governor admission state machine (ISSUE 11):
    budget resolution from the knob, the reservation ledger's
    admit→spill→reject walk, and the actionable rejection shape — no
    jax dispatches (the cold-frame spill hook is simulated)."""
    from h2o3_tpu.core import memgov
    gov = memgov.MemoryGovernor()
    resident = {"bytes": 96 << 20}
    spills = []

    def _spill(needed, exclude=None):
        # each "cold frame" releases 32MB until nothing cold remains
        if resident["bytes"] >= 32 << 20:
            resident["bytes"] -= 32 << 20
            spills.append(32 << 20)
            return 1
        return 0

    gov.bytes_in_use = lambda: resident["bytes"]
    gov.evict_for_admission = _spill
    os.environ["H2O3TPU_HBM_BUDGET_MB"] = "128"
    os.environ["H2O3TPU_MEMGOV_WAIT_S"] = "0.05"
    t0 = time.time()
    try:
        # ADMIT after one spill: 96 in use + 64 projected > 128 budget
        r1 = gov.reserve("fit-a", 64 << 20)
        assert spills, "admission must spill before admitting"
        # second fit: spills to the floor, then the ledger (fit-a's
        # 64MB hold) still blocks it -> bounded wait -> REJECT
        try:
            gov.reserve("fit-b", 96 << 20)
            raise AssertionError("over-budget fit must reject")
        except memgov.MemoryBudgetExceeded as e:
            assert e.projected == 96 << 20 and e.budget == 128 << 20
            assert "rejected before dispatch" in str(e)
        gov.release(r1)
        gov.release(gov.reserve("fit-b", 96 << 20))  # admits post-release
    finally:
        os.environ.pop("H2O3TPU_HBM_BUDGET_MB", None)
        os.environ.pop("H2O3TPU_MEMGOV_WAIT_S", None)
    dt = max(time.time() - t0, 1e-6)
    _emit("memgov admission (stub; admit->spill->reject ledger state "
          "machine, no backend)", 3 / dt, "admissions/sec", 1.0, "stub",
          spills=len(spills), rejected=1)


def _stub_ingest():
    """`ingest` line without a backend: drives the chunk PLANNER and the
    quote-aware byte-range splitter (io/chunking.py, jax-free) over a
    quoted CSV with embedded newlines/commas — every window must cut at
    a record boundary (even double-quote parity, never mid-field) and
    the windows must reassemble to the original byte stream."""
    import tempfile

    from h2o3_tpu.io import chunking
    rows = ["h1,h2"]
    for i in range(4000):
        rows.append(f'"va{i},x\ny",{i}' if i % 3 else f"v{i},{i}")
    data = ("\n".join(rows) + "\n").encode()
    d = tempfile.mkdtemp(prefix="h2o3tpu_stub_ingest_")
    path = os.path.join(d, "quoted.csv")
    with open(path, "wb") as f:
        f.write(data)
    t0 = time.time()
    windows = [w for w, _ in chunking.iter_line_chunks([path], 2048)]
    dt = max(time.time() - t0, 1e-9)
    assert b"".join(windows) == data, "splitter must be lossless"
    for w in windows:
        assert w.endswith(b"\n") and w.count(b'"') % 2 == 0, \
            "window cut mid-quote"
    plan = chunking.parse_plan([path], chunk_bytes=2048)
    assert plan["files"] == 1 and plan["est_chunks"] >= 1
    assert plan["mode"] in ("chunk-parallel", "sequential"), plan
    _emit("ingest splitter (stub; quote-aware chunk planner, no "
          "backend)", len(data) / dt / 1e6, "MB/sec", 1.0, "stub",
          windows=len(windows), mode=plan["mode"],
          workers=plan["workers"], est_chunks=plan["est_chunks"])


def _stub_serving():
    """`serving` line without a backend (ISSUE 14): drives the full
    row-parse + micro-batch queue/coalesce/scatter state machine
    (serving/rows.py + serving/batcher.py, both jax-free) — bounded
    queue saturation, deadline drops, and request coalescing — with a
    numpy dispatch standing in for the compiled scorer."""
    import threading

    from h2o3_tpu.serving.batcher import (MicroBatcher, PendingScore,
                                          QueueSaturated)
    from h2o3_tpu.serving.rows import concat_columns, parse_rows

    schema = [("x1", None), ("c1", ["a", "b", "c"])]
    widths = []

    def _dispatch(batch):
        cols = concat_columns([p.cols for p in batch])
        n = sum(p.n for p in batch)
        assert cols["x1"].shape[0] == n
        widths.append(len(batch))
        out = cols["x1"] * 2.0          # stand-in for the device program
        off = 0
        for p in batch:
            p.finish(result=out[off:off + p.n], batch_requests=len(batch))
            off += p.n

    mb = MicroBatcher("stub-model", _dispatch, max_rows=64, wait_ms=5.0,
                      queue_depth=8)
    n_clients, reqs = 4, 50
    errors = []

    def _client(cid):
        for i in range(reqs):
            cols = parse_rows(schema, [{"x1": cid + i, "c1": "b"},
                                       {"x1": None, "c1": "zzz"}])
            assert cols["c1"][0] == 1 and cols["c1"][1] == -1
            p = PendingScore(cols, 2)
            try:
                mb.submit(p)
            except QueueSaturated:
                time.sleep(0.001)
                continue
            assert p.wait(5.0) and p.error is None
            assert p.result.shape == (2,)

    t0 = time.time()
    threads = [threading.Thread(target=_client, args=(c,))
               for c in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = max(time.time() - t0, 1e-9)
    served = sum(widths)

    # saturation: an unserviced queue must 503, never block
    frozen = MicroBatcher("stub-frozen", lambda b: time.sleep(10),
                          max_rows=4, wait_ms=0.0, queue_depth=2)
    try:
        cols = parse_rows(schema, [{"x1": 1.0}])
        time.sleep(0.05)                # dispatcher is stuck in sleep
        for _ in range(2):
            frozen.submit(PendingScore(cols, 1))
        try:
            frozen.submit(PendingScore(cols, 1))
            raise AssertionError("full queue must raise QueueSaturated")
        except QueueSaturated:
            pass
        # expired deadline: failed in-queue, never dispatched
        late = PendingScore(cols, 1, deadline=time.monotonic() - 1.0)
        dead = MicroBatcher("stub-dead", _dispatch, max_rows=4,
                            wait_ms=0.0, queue_depth=4)
        try:
            dead.submit(late)
            assert late.wait(5.0)
            assert late.error is not None, "expired deadline must fail"
        finally:
            dead.close()
    finally:
        frozen.close(join=False)
    mb.close()
    _emit("serving micro-batch (stub; parse/coalesce/scatter + "
          "saturation state machine, no backend)", served / dt,
          "requests/sec", 1.0, "stub", served=served,
          dispatches=len(widths),
          mean_batch_width=round(served / max(len(widths), 1), 2),
          coalesced=any(w > 1 for w in widths))


def _stub_sched():
    """`sched` line without a backend (ISSUE 15): drives the
    scheduler's coordinator state machine (parallel/scheduler.py
    RunBoard) dry — lease → complete → dead-peer reassign → stale
    generation rejection — plus the chunked zlib+base64 blob transport
    every published result rides; no jax, no KV server."""
    from h2o3_tpu.parallel.scheduler import (RunBoard, _B64_CHUNK,
                                             _decode, _encode)
    n_items, procs = 64, [0, 1, 2, 3]
    t0 = time.time()
    board = RunBoard(n_items, procs, offset=1)
    # every item leased exactly once, rotated from the run offset
    leased = sorted(i for p in procs for i in board.assignments(p))
    assert leased == list(range(n_items))
    assert board.owner(0) == procs[1]          # offset rotation
    # half the items complete on their first owners
    for i in range(0, n_items, 2):
        assert board.on_result(i, board.owner(i), board.generation(i))
    # host 2 dies: its unresulted leases reassign over the alive hosts
    moved = board.on_dead(2)
    assert moved and all(p != 2 for _, p, _g in moved)
    assert board.on_dead(2) == []              # idempotent per host
    # a result published at the PRE-reassignment generation is ignored
    idx0, _new_pid, new_gen = moved[0]
    assert not board.on_result(idx0, 2, new_gen - 1)
    # the new owners drain everything that is left
    for p in board.alive():
        for i, g in sorted(board.assignments(p).items()):
            board.on_result(i, p, g)
    assert board.complete() and not board.pending()
    # chunked result-blob transport round-trips losslessly
    blob = os.urandom(300_000)
    b64 = _encode(blob)
    nparts = (len(b64) + _B64_CHUNK - 1) // _B64_CHUNK
    assert _decode(b64) == blob
    dt = max(time.time() - t0, 1e-6)
    _emit("sched RunBoard 64 items 4 hosts (stub; lease->complete->"
          "reassign state machine, no backend)", n_items / dt,
          "items/sec", 1.0, "stub", reassigned=len(moved),
          blob_parts=nparts)


def _stub_slo():
    """`slo` line without a backend (ISSUE 16): drives the burn-rate
    state machine (telemetry/slo.py SLOEngine) dry on a private
    registry with a fake clock — healthy → burning → alert → recovery
    → healthy, with burn-rate gauges published along the way; no jax,
    no server."""
    from h2o3_tpu.telemetry import slo
    from h2o3_tpu.telemetry.registry import MetricsRegistry
    reg = MetricsRegistry()
    clock = [1000.0]
    h = reg.histogram("predict_seconds", buckets=(0.1, 0.5, 1.0),
                      phase="device")

    rule = slo.RatioRule(
        "predict_p99_latency", objective=0.99,
        counts_fn=slo._predict_latency_counts,
        description="stub p99 rule")
    eng = slo.SLOEngine(registry=reg, rules=[rule],
                        now=lambda: clock[0])
    t0 = time.time()
    evals = 0

    def tick(dt=30.0):
        nonlocal evals
        clock[0] += dt
        evals += 1
        return eng.evaluate()

    # healthy: fast predictions only
    for _ in range(50):
        h.observe(0.01)
    out = tick()
    states = {r["slo"]: r["state"] for r in out["rules"]}
    assert states["predict_p99_latency"] == "healthy", states
    # fault-injected latency: a burst of slow predictions torches the
    # short AND long windows → burning → alert
    for _ in range(200):
        h.observe(2.0)
    saw = []
    for _ in range(12):
        out = tick()
        saw.append(out["rules"][0]["state"])
        if out["rules"][0]["state"] == "alert":
            break
    assert "alert" in saw, saw
    assert out["alerts"], "alerting rule missing from alerts list"
    # recovery: the error budget refills as fast traffic displaces the
    # burst beyond both windows
    for _ in range(80):
        for _ in range(500):
            h.observe(0.01)
        out = tick(120.0)
        if out["rules"][0]["state"] == "healthy":
            break
    assert out["rules"][0]["state"] == "healthy", out["rules"][0]
    assert not out["alerts"]
    burn = reg.find("slo_burn_rate")
    assert burn, "burn-rate gauges never published"
    trans = sum(int(c.value) for c
                in reg.find("slo_alert_transitions_total"))
    assert trans >= 2, trans  # at least alert entry + exit
    dt = max(time.time() - t0, 1e-6)
    _emit("slo burn-rate engine (stub; healthy->burning->alert->"
          "recovery on a fake clock, no backend)", evals / dt,
          "evals/sec", 1.0, "stub", transitions=trans,
          evaluations=evals)


def _stub_fleet():
    """`fleet` line without a backend (ISSUE 17): drives the replica
    router's routing/failover state machine (serving/fleet.py
    ReplicaRouter) dry on injected providers — least-loaded pick, local
    bias, heartbeat exclusion, bounded hedged failover, drain — plus
    the degradation contract (FleetUnavailable carries Retry-After);
    no jax, no sockets."""
    from h2o3_tpu.serving.fleet import (FleetUnavailable, ReplicaRouter,
                                        SERVE_LOCALLY)
    reps = {"m": {1: {}, 2: {}, 3: {}}}
    eps = {1: ("h", 1), 2: ("h", 2), 3: ("h", 3)}
    loads = {0: 0.0, 1: 5.0, 2: 1.0, 3: 9.0}
    dead, draining = set(), [False]
    r = ReplicaRouter(
        self_pid=0,
        replicas_fn=lambda mk: dict(reps.get(mk, {})),
        endpoints_fn=lambda: dict(eps),
        dead_fn=lambda: set(dead),
        loads_fn=lambda: dict(loads),
        draining_fn=lambda: draining[0],
        published_fn=lambda mk: mk == "m",
        local_bias=2.0)
    t0 = time.time()
    n_plans = 3000
    # steady state: least-loaded healthy replica wins every plan
    for _ in range(n_plans):
        p = r.plan("m", have_local=False)
        assert p.decision == "proxy" and p.pid == 2, vars(p)
    # the local bias: a swamped local replica routes away, a marginal
    # win stays local
    reps["m"][0] = {}
    loads[0] = 9.0
    assert r.plan("m", have_local=True).pid == 2
    loads[0] = 2.5
    assert r.plan("m", have_local=True).decision == "local"
    del reps["m"][0]
    # heartbeat exclusion: the best replica dies -> next-best, no probe
    dead.add(2)
    assert r.plan("m", have_local=False).pid == 1
    # bounded hedged failover: every hop down -> the fallback sentinel
    # (the caller installs the published binary), never a hang
    calls = []

    def down(pid, ep):
        calls.append(pid)
        raise ConnectionRefusedError("down")

    assert r.hedged("m", down, local_fallback=True) is SERVE_LOCALLY
    n_hedges = len(calls)
    assert n_hedges == 2            # 1 and 3 tried; 2 is dead
    # explicit degradation: no fallback -> retryable FleetUnavailable
    try:
        r.hedged("m", down)
        raise AssertionError("hedged never degraded")
    except FleetUnavailable as e:
        assert e.retry_after_s > 0
    # drain: the peer leaves routing, the published binary still
    # resolves for anyone else (install), a held copy still serves
    draining[0] = True
    assert r.plan("m", have_local=False).decision in ("proxy", "install")
    reps["m"].clear()
    assert r.plan("m", have_local=False).decision == "install"
    dt = max(time.time() - t0, 1e-6)
    _emit("fleet replica router (stub; route->bias->exclude->hedge->"
          "drain state machine, no backend)", n_plans / dt,
          "plans/sec", 1.0, "stub", hedged_hops=n_hedges)


def _stub_durability():
    """`durability` line without a backend (ISSUE 18): drives the
    registry/rebuild state machine (core/durability.py DurabilityBoard)
    dry — register → peer death → mirror-over-lineage rebuild plan on
    the least-loaded survivor → re-home acks → terminal LOST path for
    keys with neither leg — plus the chunked zlib+base64 blob transport
    mirrored frames ride over the coordination KV; no jax, no KV
    server."""
    from h2o3_tpu.core.durability import (DurabilityBoard, _B64_CHUNK,
                                          _decode, _encode)
    n_keys, procs = 64, [0, 1, 2, 3]
    t0 = time.time()
    board = DurabilityBoard(procs)
    for i in range(n_keys):
        board.register(f"frame_{i:03d}", pid=i % 4,
                       mirrored=(i % 3 != 0), lineage=(i % 3 == 0))
    # host 2 dies: every key it homed gets a rebuild plan — mirror
    # preferred over lineage, homed on the least-loaded survivor
    plan = board.on_dead(2, loads={0: 2.0, 1: 0.5, 3: 1.0})
    assert plan and all(t == 1 for _k, t, _s in plan)
    assert {s for _k, _t, s in plan} == {"mirror", "lineage"}
    assert board.on_dead(2) == []              # idempotent per host
    assert not board.complete()
    for k, t, _s in plan:
        board.on_rebuilt(k, t)
    assert board.complete()
    # a key with neither mirror nor lineage is terminally LOST on its
    # home's death — never under-replicated-forever, never a hang
    board.register("doomed", pid=3)
    plan2 = board.on_dead(3, loads={0: 0.1, 1: 9.0})
    assert all(k != "doomed" for k, _t, _s in plan2)
    assert board.lost() == ["doomed"]
    for k, t, _s in plan2:
        board.on_rebuilt(k, t)
    assert board.complete() and board.alive() == [0, 1]
    # chunked mirror-blob transport round-trips losslessly
    blob = os.urandom(300_000)
    b64 = _encode(blob)
    nparts = (len(b64) + _B64_CHUNK - 1) // _B64_CHUNK
    assert _decode(b64) == blob
    dt = max(time.time() - t0, 1e-6)
    _emit("durability board 64 frames 4 hosts (stub; register->dead->"
          "rebuild-plan->re-home state machine, no backend)",
          n_keys / dt, "frames/sec", 1.0, "stub",
          replanned=len(plan) + len(plan2), lost=len(board.lost()),
          blob_parts=nparts)


def _stub_globalfit():
    """`globalfit` line without a backend: the partitioned-ingest codec
    agreement (frame/partition.py) — per-host numeric facts / string
    levels merged deterministically must equal what one host computes
    from the concatenated rows, so every process picks the SAME column
    codec without ever seeing peer rows."""
    from h2o3_tpu.frame import partition as part
    r = np.random.RandomState(0)
    shards = [r.randn(2000) for _ in range(4)]
    for s in shards:
        s[::53] = np.nan
    ints = [np.arange(-100, 100, dtype=np.float64) * (i + 1)
            for i in range(4)]
    strs = [np.array(list("abcz"), dtype=object),
            np.array(list("bcd"), dtype=object)]
    t0 = time.time()
    n_merge = 0
    for _ in range(200):
        merged = part.merge_numeric_facts(
            [part.local_numeric_facts(s) for s in shards])
        whole = part.local_numeric_facts(np.concatenate(shards))
        assert (merged["integral"], merged["lo"], merged["hi"]) \
            == (whole["integral"], whole["lo"], whole["hi"])
        mi = part.merge_numeric_facts(
            [part.local_numeric_facts(s) for s in ints])
        assert mi["integral"] and mi["lo"] == -400.0 and mi["hi"] == 396.0
        lv = part.merge_str_levels(
            [{"levels": part.local_str_levels(s)} for s in strs])
        assert lv == part.local_str_levels(np.concatenate(strs))
        n_merge += len(shards) + len(ints) + len(strs)
    dt = max(time.time() - t0, 1e-6)
    _emit("globalfit ingest codec agreement (stub; per-host facts/"
          "levels merge == whole-rows decision, no backend)",
          n_merge / dt, "merges/sec", 1.0, "stub", rounds=200)


def _stub_stepprof():
    """`stepprof` line without a backend (ISSUE 20): the step-profiler
    phase partition + ring bound, the pure skew/straggler verdict on
    synthetic 2-peer snapshots, and scripts/benchdiff.py's pass/fail
    contract (identical pair passes, a 30% step-time regression fails)
    — all stdlib + registry, no jax."""
    import importlib.util
    import json as _json
    import tempfile
    from h2o3_tpu.telemetry import stepprof

    stepprof.reset()
    t0 = time.time()
    # -- ring bound + partition ---------------------------------------
    os.environ["H2O3TPU_STEPPROF_RING"] = "8"
    try:
        prof = stepprof.start("stub", nrows=1000)
        assert prof is not None
        for _ in range(50):
            stepprof.chunk_begin()
            stepprof.compute_done(None)
            stepprof.chunk_end()
        d = stepprof.finish(prof, model_key="stub_model", seconds=None)
    finally:
        os.environ.pop("H2O3TPU_STEPPROF_RING", None)
    assert len(d["ring"]) == 8, f"ring unbounded: {len(d['ring'])}"
    assert d["chunks"] == 50
    assert abs(sum(d["phases"].values()) - d["seconds"]) < 0.25, d
    assert stepprof.profile_for("stub_model")["algo"] == "stub"

    # -- skew verdict on synthetic 2-peer snapshots -------------------
    # peer 1 is the straggler: big SELF time, small collective wait;
    # peer 0 spent half its wall blocked at the barrier
    skew = stepprof.compute_skew({
        "0": {"proc": 0, "seconds": 10.0,
              "phases": {"host": 1.0, "compute": 4.0,
                         "collective": 5.0, "checkpoint": 0.0}},
        "1": {"proc": 1, "seconds": 10.0,
              "phases": {"host": 2.0, "compute": 7.5,
                         "collective": 0.5, "checkpoint": 0.0}}})
    assert skew["straggler_proc"] == 1, skew
    assert skew["skew_ratio"] > 1.5, skew
    assert skew["hosts"]["0"]["collective_share"] > \
        skew["hosts"]["1"]["collective_share"], skew

    # -- benchdiff pass/fail contract ---------------------------------
    bd_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "scripts", "benchdiff.py")
    spec = importlib.util.spec_from_file_location("benchdiff", bd_path)
    bd = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bd)
    with tempfile.TemporaryDirectory() as tmp:
        old = os.path.join(tmp, "old.json")
        new = os.path.join(tmp, "new.json")
        base = [{"metric": "fit_step", "value": 1.0, "unit": "seconds",
                 "phases": {"host": 0.2, "compute": 0.8}},
                {"metric": "gbm_rows", "value": 1e6, "unit": "rows/sec"}]
        regressed = [{"metric": "fit_step", "value": 1.3,
                      "unit": "seconds",
                      "phases": {"host": 0.2, "compute": 1.1}},
                     {"metric": "gbm_rows", "value": 1e6,
                      "unit": "rows/sec"}]
        with open(old, "w") as f:
            _json.dump(base, f)
        with open(new, "w") as f:
            _json.dump(regressed, f)
        rc_same = bd.main([old, old])
        rc_reg = bd.main([old, new])
    assert rc_same == 0, f"identical pair must pass, rc={rc_same}"
    assert rc_reg == 1, f"30% regression must fail, rc={rc_reg}"

    dt = max(time.time() - t0, 1e-6)
    _emit("stepprof phase partition + skew verdict + benchdiff gate "
          "(stub; ring bound, straggler id on synthetic peers, "
          "regression pass/fail, no backend)",
          50 / dt, "chunks/sec", 1.0, "stub",
          ring_len=len(d["ring"]), straggler=skew["straggler_proc"],
          skew_ratio=skew["skew_ratio"],
          benchdiff_identical_rc=rc_same, benchdiff_regression_rc=rc_reg)


if STUB:
    CONFIGS = [("stub_a", _stub_ok("stub_a")),
               ("stub_wedge", _stub_wedge),
               ("grid", _stub_grid),
               ("treekernel", _stub_treekernel),
               ("cloud", _stub_cloud),
               ("roofline", _stub_roofline),
               ("checkpoint", _stub_checkpoint),
               ("memgov", _stub_memgov),
               ("ingest", _stub_ingest),
               ("serving", _stub_serving),
               ("sched", _stub_sched),
               ("slo", _stub_slo),
               ("fleet", _stub_fleet),
               ("durability", _stub_durability),
               ("globalfit", _stub_globalfit),
               ("stepprof", _stub_stepprof),
               ("stub_b", _stub_ok("stub_b"))]
    _MIN_NEED = {n: 1 for n, _ in CONFIGS}
    _HARD_CAP = {n: 30 for n, _ in CONFIGS}


def _hard_cap(name) -> float:
    env = float(os.environ.get("H2O3TPU_BENCH_CONFIG_TIMEOUT_S", "0") or 0)
    return env or float(_HARD_CAP.get(name, 600))


# ---------------------------------------------------------- child modes


def _emit_hardening(name: str) -> None:
    """Request-hardening counters for this config's process (ISSUE 3):
    how many requests were rejected by the admission gate and how many
    deadlines expired while the config ran. Non-zero numbers mean the
    measured wall times include overload shedding — the scoreboard must
    say so."""
    try:
        from h2o3_tpu import telemetry
        _emit_raw({
            "metric": f"request-hardening {name}",
            "rest_rejected_total":
                int(telemetry.REGISTRY.total("rest_rejected_total")),
            "request_deadline_exceeded_total": int(
                telemetry.REGISTRY.value("request_deadline_exceeded_total")),
            "rest_client_disconnects_total": int(
                telemetry.REGISTRY.value("rest_client_disconnects_total"))})
    except Exception:   # noqa: BLE001 - accounting must never fail a config
        pass


def _emit_trace(name: str) -> None:
    """Write this config's process trace (spans + timeline + compiles)
    as a Chrome trace-event artifact so a BENCH run is explorable in
    Perfetto — where the wall time of a slow config actually went
    (compile track vs chunk spans), not just its final number."""
    try:
        from h2o3_tpu.telemetry import trace_export
        out_dir = os.environ.get("H2O3TPU_BENCH_TRACE_DIR",
                                 "/tmp/h2o3tpu_bench_traces")
        path = os.path.join(out_dir, f"trace_{name}.json")
        trace = trace_export.process_trace()
        trace_export.write_trace(path, trace)
        _emit_raw({"metric": f"trace {name}", "trace_path": path,
                   "trace_events": len(trace["traceEvents"])})
    except Exception:   # noqa: BLE001 - artifacts must never fail a config
        pass


def _child_one(name: str) -> int:
    """Run exactly one config in THIS process (spawned by the parent).
    Metric lines go to stdout; failures leave a classified traceback on
    stderr for the parent and exit nonzero."""
    fn = dict(CONFIGS)[name]
    if not STUB:
        import h2o3_tpu
        h2o3_tpu.init()
    try:
        fn()
        _emit_hardening(name)
        _emit_trace(name)
        return 0
    except Exception as e:   # noqa: BLE001 - child boundary
        import traceback
        traceback.print_exc(file=sys.stderr)
        print(f"# child-error {name}: {e!r}"[:300], file=sys.stderr)
        return 1


def _stub_probe() -> int:
    """STUB-mode probe without the package import. Replicates
    watchdog.maybe_fail("probe") + _consume_shared over the same env
    contract (H2O3TPU_FAULTS / H2O3TPU_FAULT_STATE) in pure stdlib:
    the harness tests spawn ~50 probe children per run, and each
    ``from h2o3_tpu.core import watchdog`` costs ~1s of package import
    to reach a hook that needs only os/time."""
    site = "probe"
    count, sign = 0, "UNAVAILABLE"
    for part in os.environ.get("H2O3TPU_FAULTS", "").split(","):
        bits = part.strip().split(":")
        if bits[0] != site:
            continue
        count = int(bits[1]) if len(bits) > 1 and bits[1] else 1
        if len(bits) > 2 and bits[2]:
            sign = bits[2]
        break
    if count <= 0:
        return 0
    state = os.environ.get("H2O3TPU_FAULT_STATE") or None
    fail = True         # a fresh process always has its budget left
    if state is not None:
        path = os.path.join(state, f"fault_{site}.count")
        os.makedirs(state, exist_ok=True)
        lock = path + ".lock"
        for _ in range(200):                      # ~2s worst case
            try:
                fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
                break
            except FileExistsError:
                time.sleep(0.01)
        try:
            consumed = 0
            if os.path.exists(path):
                with open(path) as f:
                    consumed = int(f.read().strip() or 0)
            fail = consumed < count
            if fail:
                with open(path, "w") as f:
                    f.write(str(consumed + 1))
        finally:
            try:
                os.unlink(lock)
            except OSError:
                pass
    if fail:
        print("# probe failed: InjectedFault(\"%s: injected fault at "
              "site '%s'\")" % (sign, site), file=sys.stderr)
        return 1
    return 0


def _child_probe() -> int:
    """Backend liveness probe in a fresh process (core/watchdog.py):
    jax.devices() + a tiny device_put round-trip. In stub mode only the
    fault-injection hook runs — the harness under test, not the chip."""
    if STUB:
        return _stub_probe()
    from h2o3_tpu.core import watchdog
    try:
        rt = watchdog.probe_backend()
        print(f"# probe ok ({rt:.2f}s)", file=sys.stderr)
        return 0
    except Exception as e:   # noqa: BLE001 - child boundary
        print(f"# probe failed: {e!r}"[:300], file=sys.stderr)
        return 1


# --------------------------------------------------------------- parent


def _spawn(args, timeout_s, extra_env=None):
    """Run a child; returns (rc, stdout, stderr_tail). rc=124 on
    timeout (child and its process group killed)."""
    import subprocess
    env = dict(os.environ)
    env.update(extra_env or {})
    try:
        p = subprocess.run([sys.executable, os.path.abspath(__file__)]
                           + args, env=env, capture_output=True,
                           text=True, timeout=timeout_s)
        return p.returncode, p.stdout, p.stderr[-2000:]
    except subprocess.TimeoutExpired as e:
        out = e.stdout or b""
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        return 124, out, f"timeout after {timeout_s:.0f}s (child killed)"


def _passthrough(stdout: str) -> int:
    """Re-emit the child's metric lines from the parent (the driver
    tails PARENT stdout; the tail-proof summary needs them recorded
    here). Returns how many metric lines came through."""
    n = 0
    for ln in stdout.splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            try:
                _emit_raw(json.loads(ln))
                n += 1
                continue
            except ValueError:
                pass
        if ln:
            print(ln, flush=True)
    return n


def _last_line(err: str, cap: int = 160) -> str:
    """The final non-empty stderr line, bounded — the one-line summary
    of a failure (never the full backend traceback)."""
    lines = [ln for ln in err.strip().splitlines() if ln.strip()]
    return lines[-1][:cap] if lines else ""


def _preflight(name: str, policy):
    """Probe the backend from a fresh process under the shared retry
    policy. Returns ``None`` when healthy, else a one-line reason —
    backend dead after bounded backoff; fail fast on this config
    instead of feeding it to a corpse. Each failed attempt costs ONE
    bounded stderr note (the scoreboard contract: a dead backend is one
    ``{"metric", "error"}`` line per config, never traceback spam)."""
    reason = ""
    for attempt in range(1, policy.max_attempts + 1):
        budget = min(_hard_cap(name), max(_remaining(), 5.0)) + 30.0
        rc, _, err = _spawn(["--probe"], timeout_s=budget)
        if rc == 0:
            return None
        reason = _last_line(err) or f"probe rc={rc}"
        print(f"# preflight {name}: probe attempt {attempt}/"
              f"{policy.max_attempts} failed: {reason}",
              file=sys.stderr)
        if attempt < policy.max_attempts and _remaining() > 0:
            time.sleep(policy.delay(attempt))
    return reason or "probe failed"


def main():
    import atexit
    atexit.register(_print_summary)
    # policy only — the parent must NEVER touch the backend itself (a
    # wedged chip would take the whole scoreboard down with it)
    from h2o3_tpu.core import watchdog
    policy = watchdog.policy_from_config()
    filt = sys.argv[1] if len(sys.argv) > 1 else ""
    force_full = os.environ.get("H2O3TPU_BENCH_FULL") == "1"
    for name, _fn in CONFIGS:
        if filt:
            # explicit selection: substring match, except the escalation
            # config which must be named exactly ("gbm" must not also
            # kick off the 50M-row run)
            if name == "gbm-full":
                if filt != "gbm-full":
                    continue
            elif filt not in name:
                continue
        elif name == "gbm-full" and not force_full \
                and _remaining() < _MIN_NEED[name]:
            _emit_raw({"metric": name,
                       "skipped": f"budget ({_remaining():.0f}s left)"})
            continue
        elif name != "gbm-full" and _remaining() < _MIN_NEED.get(name, 60):
            _emit_raw({"metric": name,
                       "skipped": f"budget ({_remaining():.0f}s left)"})
            continue
        for attempt in range(1, policy.max_attempts + 1):
            probe_err = _preflight(name, policy)
            if probe_err is not None:
                _emit_raw({"metric": name,
                           "error": "backend dead (pre-flight probe "
                                    "failed after bounded backoff): "
                                    + probe_err})
                break
            cap = min(_hard_cap(name), max(_remaining(), 10.0))
            rc, out, err = _spawn(
                ["--one", name], timeout_s=cap,
                # child budget = what is left HERE, so in-config caps
                # (automl max_runtime_secs) see the parent's clock
                extra_env={"H2O3TPU_BENCH_BUDGET_S":
                           f"{max(_remaining(), 10.0):.0f}"})
            emitted = _passthrough(out)
            if rc == 0:
                if err.strip():     # child progress notes (ingest etc.)
                    sys.stderr.write(err if err.endswith("\n")
                                     else err + "\n")
                break
            if rc == 124:
                _emit_raw({"metric": name,
                           "error": f"wedged: killed after {cap:.0f}s "
                                    f"hard cap ({emitted} lines emitted)"})
                break   # a kill is a wedge, not a blip: don't re-feed it
            infra = any(s in err for s in _INFRA_SIGNS)
            if (not infra or attempt >= policy.max_attempts
                    or _remaining() < _MIN_NEED.get(name, 60)):
                # ONE bounded line each to stderr and the scoreboard —
                # never the child's full traceback (round-5 spam)
                summary = _last_line(err, 300) or f"child rc={rc}"
                print(f"# {name}: child failed: {summary}",
                      file=sys.stderr)
                _emit_raw({"metric": name, "error": summary})
                break
            d = policy.delay(attempt)
            print(f"# retrying {name} after infra error in {d:.0f}s "
                  f"(attempt {attempt}/{policy.max_attempts})",
                  file=sys.stderr)
            time.sleep(d)
    # left_s is clamped ≥ 0 (used_s stays honest about any overrun)
    _emit_raw({"metric": "budget",
               "budget_s": round(BUDGET_S, 1),
               "used_s": round(time.time() - _T0, 1),
               "left_s": round(_remaining(), 1)})
    _print_summary()


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--one":
        sys.exit(_child_one(sys.argv[2]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--probe":
        sys.exit(_child_probe())
    else:
        main()
