"""Synthesize smalldata files the curated pyunits need but that don't
exist anywhere in this environment.

These are schema-compatible stand-ins (same column names/types/rough
distributions as the well-known public datasets), generated with fixed
seeds — NOT copies. Tests that assert exact golden values against the
original data are excluded from the curated list instead.
"""

from __future__ import annotations

import os

import numpy as np


def _write_csv(path: str, header: list, cols: list) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    if os.path.exists(path):
        return
    n = len(cols[0])
    with open(path, "w") as f:
        f.write(",".join(header) + "\n")
        for i in range(n):
            f.write(",".join("" if v is None else str(c[i])
                             for c, v in ((c, c[i]) for c in cols)) + "\n")


def gen_cars(sd: str) -> None:
    """cars_20mpg.csv: mpg-classification set (schema of the classic
    'cars' data: name,economy,cylinders,displacement,power,weight,
    acceleration,year,economy_20mpg)."""
    r = np.random.RandomState(42)
    n = 406
    cyl = r.choice([3, 4, 5, 6, 8], n, p=[0.01, 0.5, 0.01, 0.21, 0.27])
    disp = (cyl * 40 + r.randn(n) * 25).round(1)
    power = (cyl * 20 + r.randn(n) * 15).round(0)
    weight = (cyl * 500 + r.randn(n) * 300).round(0)
    accel = (25 - cyl + r.randn(n) * 2).round(1)
    year = r.randint(70, 83, n)
    econ = (50 - 3.5 * cyl + (year - 70) * 0.5 + r.randn(n) * 3).round(1)
    econ20 = (econ >= 20).astype(int)
    name = [f"car_{i}" for i in range(n)]
    # pyunit_trim asserts the first three trimmed names verbatim (the
    # real cars data starts with the AMC Ambassador series)
    name[:3] = ["AMC Ambassador Brougham", "AMC Ambassador DPL",
                "AMC Ambassador SST"]
    _write_csv(os.path.join(sd, "junit/cars_20mpg.csv"),
               ["name", "economy", "cylinders", "displacement", "power",
                "weight", "acceleration", "year", "economy_20mpg"],
               [name, econ, cyl, disp, power, weight, accel, year, econ20])


def gen_benign(sd: str) -> None:
    """logreg/benign.csv: 14 numeric cols, binary FNDX response."""
    r = np.random.RandomState(7)
    n = 189
    names = ["STR", "OBS", "AGMT", "FNDX", "HIGD", "DEG", "CHK",
             "AGP1", "AGMN", "NLV", "LIV", "WT", "AGLP", "MST"]
    data = [r.randint(1, 5, n), np.arange(1, n + 1), r.randint(30, 65, n)]
    fndx = r.binomial(1, 0.3, n)
    data.append(fndx)
    for _ in range(10):
        data.append((r.randn(n) * 10 + 30).round(0).astype(int))
    _write_csv(os.path.join(sd, "logreg/benign.csv"), names, data)


def gen_insurance(sd: str) -> None:
    """glm_test/insurance.csv: District,Group,Age,Holders,Claims."""
    r = np.random.RandomState(11)
    dist, grp, age = [], [], []
    groups = ["<1l", "1-1.5l", "1.5-2l", ">2l"]
    ages = ["<25", "25-29", "30-35", ">35"]
    for d in range(1, 5):
        for g in groups:
            for a in ages:
                dist.append(d)
                grp.append(g)
                age.append(a)
    n = len(dist)
    holders = r.randint(10, 500, n)
    lam = holders * 0.12
    claims = r.poisson(lam)
    _write_csv(os.path.join(sd, "glm_test/insurance.csv"),
               ["District", "Group", "Age", "Holders", "Claims"],
               [dist, grp, age, holders, claims])


def gen_higgs_sample(sd: str) -> None:
    """testng/higgs_train_5k.csv / higgs_test_5k.csv: response + 28 num."""
    for fname, seed, n in (("higgs_train_5k.csv", 3, 5000),
                           ("higgs_test_5k.csv", 4, 5000)):
        r = np.random.RandomState(seed)
        y = r.binomial(1, 0.53, n)
        feats = [(r.randn(n) + 0.2 * y).round(6) for _ in range(28)]
        _write_csv(os.path.join(sd, "testng", fname),
                   ["response"] + [f"x{i}" for i in range(1, 29)],
                   [y] + feats)


def gen_airlines(sd: str) -> None:
    """airlines/allyears2k_headers.zip stand-in as csv (common columns)."""
    import zipfile
    r = np.random.RandomState(5)
    n = 2000
    year = r.randint(1987, 2009, n)
    month = r.randint(1, 13, n)
    dom = r.randint(1, 29, n)
    dow = r.randint(1, 8, n)
    crsdep = r.randint(0, 2400, n)
    deptime = crsdep + r.randint(-10, 60, n)
    crsarr = (crsdep + r.randint(30, 360, n)) % 2400   # pyunit_ifelse
    arrtime = (crsarr + r.randint(-20, 90, n)) % 2400
    origin = r.choice(["SFO", "JFK", "ORD", "ATL", "DEN"], n)
    dest = r.choice(["LAX", "BOS", "SEA", "MIA", "PHX"], n)
    dist = r.randint(100, 2500, n)
    carrier = r.choice(["UA", "AA", "DL", "WN"], n)
    depdelay = np.maximum(deptime - crsdep, 0)
    isdelayed = np.where(depdelay > 15, "YES", "NO")
    path = os.path.join(sd, "airlines/allyears2k_headers.zip")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    if not os.path.exists(path):
        import io
        buf = io.StringIO()
        hdr = ["Year", "Month", "DayofMonth", "DayOfWeek", "DepTime",
               "CRSDepTime", "ArrTime", "CRSArrTime", "UniqueCarrier",
               "Origin", "Dest", "Distance", "DepDelay", "IsDepDelayed"]
        buf.write(",".join(hdr) + "\n")
        for i in range(n):
            buf.write(f"{year[i]},{month[i]},{dom[i]},{dow[i]},"
                      f"{deptime[i]},{crsdep[i]},{arrtime[i]},{crsarr[i]},"
                      f"{carrier[i]},{origin[i]},"
                      f"{dest[i]},{dist[i]},{depdelay[i]},{isdelayed[i]}\n")
        with zipfile.ZipFile(path, "w") as z:
            z.writestr("allyears2k_headers.csv", buf.getvalue())


def gen_prostate_variants(sd: str) -> None:
    """logreg/prostate.csv + train/test splits, derived from the real
    prostate data already linked at smalldata/prostate/prostate.csv
    (the reference's logreg variants drop ID and pre-split the rows)."""
    src = os.path.join(sd, "prostate/prostate.csv")
    if not os.path.exists(src):
        return
    with open(src) as f:
        header = f.readline().strip().split(",")
        rows = [ln.strip().split(",") for ln in f if ln.strip()]
    os.makedirs(os.path.join(sd, "logreg"), exist_ok=True)
    full = os.path.join(sd, "logreg/prostate.csv")
    if not os.path.exists(full):
        with open(full, "w") as f:
            f.write(",".join(header) + "\n")
            f.writelines(",".join(r) + "\n" for r in rows)
    # train/test: no ID column, CAPSULE first, deterministic 70/30 split
    idx = header.index("CAPSULE")
    keep = [idx] + [i for i in range(len(header))
                    if header[i] not in ("ID", "CAPSULE")]
    r = np.random.RandomState(17)
    mask = r.rand(len(rows)) < 0.7
    for name, sel in (("prostate_train.csv", mask),
                      ("prostate_test.csv", ~mask)):
        path = os.path.join(sd, "logreg", name)
        if os.path.exists(path):
            continue
        with open(path, "w") as f:
            f.write(",".join(header[i] for i in keep) + "\n")
            for j, row in enumerate(rows):
                if sel[j]:
                    f.write(",".join(row[i] for i in keep) + "\n")


def gen_airlines_train_test(sd: str) -> None:
    """AirlinesTrain/AirlinesTest.csv.zip stand-ins (schema of the real
    files: factor-prefixed calendar columns + IsDepDelayed)."""
    import zipfile
    for fname, seed, n in (("AirlinesTrain.csv.zip", 21, 6000),
                           ("AirlinesTest.csv.zip", 22, 3000)):
        path = os.path.join(sd, "airlines", fname)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        if os.path.exists(path):
            continue
        r = np.random.RandomState(seed)
        carriers = np.array(["UA", "AA", "DL", "WN"])
        ports = np.array(["SFO", "JFK", "ORD", "ATL", "DEN", "LAX"])
        import io
        buf = io.StringIO()
        hdr = ["fYear", "fMonth", "fDayofMonth", "fDayOfWeek", "DepTime",
               "ArrTime", "UniqueCarrier", "Origin", "Dest", "Distance",
               "IsDepDelayed", "IsDepDelayed_REC"]
        buf.write(",".join(hdr) + "\n")
        for i in range(n):
            mo = r.randint(1, 13)
            dow = r.randint(1, 8)
            dep = r.randint(0, 2400)
            carrier = carriers[r.randint(0, len(carriers))]
            delayed = (0.03 * (dep - 1000) + (carrier == "UA") * 15
                       + (mo in (12, 1, 6)) * 8 + r.randn() * 25) > 15
            buf.write(
                f"f{1987 + r.randint(0, 20)},f{mo},f{r.randint(1, 29)},"
                f"f{dow},{dep},{(dep + r.randint(30, 300)) % 2400},"
                f"{carrier},{ports[r.randint(0, len(ports))]},"
                f"{ports[r.randint(0, len(ports))]},"
                f"{r.randint(100, 2500)},"
                f"{'YES' if delayed else 'NO'},{1 if delayed else -1}\n")
        with zipfile.ZipFile(path, "w") as z:
            z.writestr(fname[:-4], buf.getvalue())


def gen_prostate_complete(sd: str) -> None:
    """prostate_complete.csv.zip: complete-case prostate stand-in (the
    real file is the same schema with no missing rows)."""
    import zipfile
    src = os.path.join(sd, "prostate/prostate.csv")
    dst = os.path.join(sd, "prostate/prostate_complete.csv.zip")
    if not os.path.exists(src) or os.path.exists(dst):
        return
    with zipfile.ZipFile(dst, "w") as z:
        z.write(src, "prostate_complete.csv")


def gen_munging_files(sd: str) -> None:
    """Small files the munging pyunits need: cars.csv (cars_20mpg minus
    the binary response), cars_trim.csv (whitespace-padded name column),
    names.csv (string columns), prostate variants with injected NAs, and
    an iris train split."""
    gen_cars(sd)
    src = os.path.join(sd, "junit/cars_20mpg.csv")
    with open(src) as f:
        header = f.readline().strip().split(",")
        rows = [ln.rstrip("\n").split(",") for ln in f if ln.strip()]
    keep = [i for i, h in enumerate(header) if h != "economy_20mpg"]
    # the real junit/cars.csv carries unit-suffixed headers; the ordinal
    # GLM pyunit (pyunit_pubdev_8194_ordinal_fail) selects them by name
    cars_names = {"economy": "economy (mpg)",
                  "displacement": "displacement (cc)",
                  "power": "power (hp)", "weight": "weight (lb)",
                  "acceleration": "0-60 mph (s)"}
    p = os.path.join(sd, "junit/cars.csv")
    if not os.path.exists(p):
        with open(p, "w") as f:
            f.write(",".join(cars_names.get(header[i], header[i])
                             for i in keep) + "\n")
            f.writelines(",".join(r[i] for i in keep) + "\n" for r in rows)
    p = os.path.join(sd, "junit/cars_trim.csv")
    if not os.path.exists(p):
        with open(p, "w") as f:
            f.write(",".join(header[i] for i in keep) + "\n")
            for r in rows:
                padded = ['"  %s  "' % r[keep[0]]] + \
                    [r[i] for i in keep[1:]]
                f.write(",".join(padded) + "\n")
    p = os.path.join(sd, "junit/names.csv")
    if not os.path.exists(p):
        # pyunit_length contract: name1 (UTF), name2 (ASCII), numeric;
        # first three rows have nchar 4, 3, 4 in both name columns
        rng = np.random.RandomState(9)
        utf = ["ánna", "bób", "cárl", "dóra", "érin", "fráu"]
        ascii_ = ["anna", "bob", "carl", "dora", "erin", "fran"]
        with open(p, "w", encoding="utf-8") as f:
            f.write("name1,name2,string_lengths\n")
            for i in range(100):
                j = i % 6 if i >= 3 else i
                f.write(f"{utf[j]},{ascii_[j]},{len(ascii_[j])}\n")
    # prostate with injected NAs (prostate_missing / prostate_NA roles)
    psrc = os.path.join(sd, "prostate/prostate.csv")
    if os.path.exists(psrc):
        with open(psrc) as f:
            ph = f.readline()
            prows = [ln.rstrip("\n").split(",") for ln in f if ln.strip()]
        rng = np.random.RandomState(13)
        for rel in ("logreg/prostate_missing.csv",
                    "parser/csv2orc/prostate_NA.csv"):
            p = os.path.join(sd, rel)
            if os.path.exists(p):
                continue
            os.makedirs(os.path.dirname(p), exist_ok=True)
            with open(p, "w") as f:
                f.write(ph)
                for r in prows:
                    out = list(r)
                    for j in range(2, len(out)):
                        if rng.rand() < 0.05:
                            out[j] = ""
                    f.write(",".join(out) + "\n")
    # iris train split (multinomial GLM pyunits)
    isrc = os.path.join(sd, "iris/iris_wheader.csv")
    p = os.path.join(sd, "iris/iris_train.csv")
    if os.path.exists(isrc) and not os.path.exists(p):
        with open(isrc) as f:
            ih = f.readline()
            irows = [ln for ln in f if ln.strip()]
        rng = np.random.RandomState(21)
        sel = rng.rand(len(irows)) < 0.8
        with open(p, "w") as f:
            # the reference's iris_train.csv names the target "species"
            # (pyunit_PUBDEV_6062 trains y="species"), unlike
            # iris_wheader's "class"
            f.write(ih.replace("class", "species"))
            f.writelines(ln for i, ln in enumerate(irows) if sel[i])


def gen_jira_files(sd: str) -> None:
    """pub-180.csv (12x4, pyunit_cbind asserts names/dims) + v-11.csv
    (different row count, used as the unequal-rows cbind failure)."""
    r = np.random.RandomState(18)
    n = 12
    _write_csv(os.path.join(sd, "jira/pub-180.csv"),
               ["colgroup", "colgroup2", "col1", "col2"],
               [r.randint(0, 5, n), r.randint(0, 5, n),
                r.randint(0, 10, n), r.randint(0, 10, n)])
    m = 11
    _write_csv(os.path.join(sd, "jira/v-11.csv"),
               ["vcol1", "vcol2"],
               [r.randint(0, 9, m), np.round(r.rand(m), 3)])


def gen_chicago_crimes(sd: str) -> None:
    """chicagoCrimes10k.csv.zip: a Date column in the real data's
    'MM/dd/yyyy hh:mm:ss a' format (pyunit_count_temps date munging)."""
    import zipfile
    path = os.path.join(sd, "chicago/chicagoCrimes10k.csv.zip")
    if os.path.exists(path):
        return
    os.makedirs(os.path.dirname(path), exist_ok=True)
    r = np.random.RandomState(23)
    n = 10_000
    mo = r.randint(1, 13, n)
    day = r.randint(1, 29, n)
    hr12 = r.randint(1, 13, n)
    mi = r.randint(0, 60, n)
    se = r.randint(0, 60, n)
    ampm = np.where(r.rand(n) < 0.5, "AM", "PM")
    dates = [f"{mo[i]:02d}/{day[i]:02d}/2015 "
             f"{hr12[i]:02d}:{mi[i]:02d}:{se[i]:02d} {ampm[i]}"
             for i in range(n)]
    ptype = r.choice(["THEFT", "BATTERY", "NARCOTICS", "ASSAULT"], n)
    arrest = r.choice(["true", "false"], n)
    rows = ["ID,Date,Primary Type,Arrest,Beat"]
    rows += [f"{100000 + i},{dates[i]},{ptype[i]},{arrest[i]},"
             f"{r.randint(111, 2535)}" for i in range(n)]
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("chicagoCrimes10k.csv", "\n".join(rows) + "\n")


def gen_allyears2k(sd: str) -> None:
    """allyears2k.zip: airlines-schema zip (pyunit_frame_show only
    displays it — schema-compatible sample, 2000 rows)."""
    import zipfile
    path = os.path.join(sd, "airlines/allyears2k.zip")
    if os.path.exists(path):
        return
    os.makedirs(os.path.dirname(path), exist_ok=True)
    r = np.random.RandomState(2000)
    n = 2000
    carriers = ["UA", "AA", "DL", "WN", "US", "NW"]
    rows = ["Year,Month,DayofMonth,DayOfWeek,DepTime,UniqueCarrier,"
            "Origin,Dest,Distance,IsDepDelayed"]
    for i in range(n):
        rows.append(
            f"{r.randint(1987, 2009)},{r.randint(1, 13)},"
            f"{r.randint(1, 29)},{r.randint(1, 8)},{r.randint(0, 2400)},"
            f"{carriers[r.randint(0, len(carriers))]},"
            f"ORD,SFO,{r.randint(100, 2500)},"
            f"{'YES' if r.rand() < 0.5 else 'NO'}")
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("allyears2k.csv", "\n".join(rows) + "\n")


def gen_small_int_floats(sd: str) -> None:
    """smallIntFloats.csv.zip: two numeric columns with ties (the
    property-checked descending/ascending sort pyunit)."""
    import zipfile
    path = os.path.join(sd, "synthetic/smallIntFloats.csv.zip")
    if os.path.exists(path):
        return
    os.makedirs(os.path.dirname(path), exist_ok=True)
    r = np.random.RandomState(44)
    n = 5000
    a = r.randint(-50, 50, n)
    b = np.round(r.randn(n) * 100, 4)
    rows = ["IntCol,FloatCol"]
    rows += [f"{a[i]},{b[i]}" for i in range(n)]
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("smallIntFloats.csv", "\n".join(rows) + "\n")


def generate_all(sd: str) -> None:
    gen_cars(sd)
    gen_benign(sd)
    gen_insurance(sd)
    gen_higgs_sample(sd)
    gen_airlines(sd)
    gen_prostate_variants(sd)
    gen_prostate_complete(sd)
    gen_airlines_train_test(sd)
    gen_munging_files(sd)
    gen_jira_files(sd)
    gen_chicago_crimes(sd)
    gen_allyears2k(sd)
    gen_small_int_floats(sd)
