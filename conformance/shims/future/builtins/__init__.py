"""future.builtins on python 3 == the builtins module."""
from builtins import *          # noqa: F401,F403
from builtins import (chr, input, open, next, round, super,  # noqa: F401
                      range, filter, map, zip)
