from builtins import range, filter, map, zip   # noqa: F401
