from builtins import chr, input, open, next, round, super   # noqa: F401
