"""future.standard_library — no-op on python 3."""


def install_aliases():
    pass
