"""future.utils for python 3: constants and dict views are native."""

PY2 = False
PY3 = True


def with_metaclass(meta, *bases):
    """Create a base class with a metaclass (classic recipe)."""
    class metaclass(type):
        def __new__(cls, name, this_bases, d):
            if this_bases is None:
                return type.__new__(cls, name, (), d)
            return meta(name, bases, d)
    return metaclass("temporary_class", None, {})


def viewitems(d, **kw):
    return d.items(**kw)


def viewkeys(d, **kw):
    return d.keys(**kw)


def viewvalues(d, **kw):
    return d.values(**kw)
