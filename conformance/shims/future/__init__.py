"""Minimal py3-only stand-in for the `future` package.

The reference h2o-py client (h2o-py/h2o/utils/compatibility.py:64) imports
a handful of names from `future`; the real package is a py2/py3 bridge that
is pure pass-through on python 3. This shim provides exactly those names so
the unmodified client can run in this environment (no pip installs).
"""

from . import standard_library   # noqa: F401
