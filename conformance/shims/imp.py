"""`imp` stdlib module shim for python >= 3.12 (removed upstream).

The reference h2o-py test utils only use imp.load_source
(h2o-py/tests/pyunit_utils/utilsPY.py), reimplemented here on importlib.
"""

import importlib.util
import sys


def load_source(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def new_module(name):
    import types
    return types.ModuleType(name)


def find_module(name, path=None):
    """utilsPY.py:350 probes numpy availability; mimic the old
    contract: raise ImportError when absent, return a truthy spec."""
    spec = importlib.util.find_spec(name)
    if spec is None:
        raise ImportError(f"No module named {name!r}")
    return spec
