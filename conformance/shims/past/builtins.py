"""past.builtins on python 3: py2 names mapped to py3 equivalents."""

basestring = str
unicode = str
long = int


def xrange(*a):
    return range(*a)


def cmp(a, b):
    return (a > b) - (a < b)
