"""Minimal py3-only stand-in for the `past` package (see future/)."""
