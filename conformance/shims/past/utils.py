"""`past.utils` shim (python-future, removed): only old_div is used
by the reference pyunits (e.g. testdir_munging/pyunit_ifelse.py)."""


def old_div(a, b):
    """Py2 `/` semantics: floor division for two ints, true division
    otherwise — including elementwise objects like H2OFrame, whose
    __div__/__floordiv__ operators the expression layer provides."""
    import numbers
    if isinstance(a, numbers.Integral) and isinstance(b, numbers.Integral):
        return a // b
    return a / b
