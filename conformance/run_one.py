"""Run ONE reference pyunit against an already-running h2o3-tpu server.

Usage: python conformance/run_one.py <server-url> <pyunit-path> <workdir>

The pyunit is executed unmodified with run_name="__main__";
pyunit_utils.standalone_test sees the pre-opened connection and skips
h2o.init (h2o-py/tests/pyunit_utils/utilsPY.py:689).
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_PY = "/root/reference/h2o-py"

sys.path.insert(0, os.path.join(REPO, "conformance", "shims"))
sys.path.insert(0, REF_PY)
sys.path.insert(0, os.path.join(REF_PY, "tests"))

url, pyunit, workdir = sys.argv[1], sys.argv[2], sys.argv[3]
os.chdir(workdir)    # so pyunit_utils.locate finds the smalldata farm

import h2o                                   # noqa: E402
h2o.connect(url=url, verbose=False, strict_version_check=False)

# Disable per-call progress bars: they spam the captured output
try:
    h2o.no_progress()
except Exception:
    pass

import runpy                                  # noqa: E402
runpy.run_path(pyunit, run_name="__main__")
print("PYUNIT-PASS")
