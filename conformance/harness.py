"""Conformance harness: run the REAL h2o-py client against our server.

Usage:
    python conformance/harness.py smoke          # connect+train smoke test
    python conformance/harness.py pyunit <file>  # run one reference pyunit

The reference client is imported unmodified from /root/reference/h2o-py
(plus the tiny `future` shim in conformance/shims). Datasets referenced as
smalldata/... are resolved through a symlink farm built at runtime in a
temp dir — no reference files are copied into the repo.
"""

from __future__ import annotations

import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_PY = "/root/reference/h2o-py"

sys.path.insert(0, os.path.join(REPO, "conformance", "shims"))
sys.path.insert(0, REF_PY)
sys.path.insert(0, REPO)

# Map smalldata-relative paths → real files available in this environment.
# Only genuinely-present reference data files are linked; everything else
# is synthesized by gen_data.py with the right schema.
SMALLDATA_LINKS = {
    "prostate/prostate.csv": f"{REF_PY}/h2o/h2o_data/prostate.csv",
    "prostate/prostate.csv.zip": None,     # synthesized (zip of the csv)
    # the real smalldata/iris/iris.csv is HEADERLESS (pyunits genfromtxt
    # it); synthesized from the headered extdata copy in build_smalldata
    "iris/iris.csv": None,
    "iris/iris_wheader.csv": "/root/reference/h2o-r/h2o-package/inst/extdata/iris_wheader.csv",
    "extdata/australia.csv": "/root/reference/h2o-core/src/main/resources/extdata/australia.csv",
    "extdata/housevotes.csv": "/root/reference/h2o-core/src/main/resources/extdata/housevotes.csv",
    "extdata/walking.csv": "/root/reference/h2o-r/h2o-package/inst/extdata/walking.csv",
}


def build_smalldata(root: str) -> str:
    """Create the smalldata/ symlink+synthetic farm under `root`."""
    sd = os.path.join(root, "smalldata")
    for rel, src in SMALLDATA_LINKS.items():
        dst = os.path.join(sd, rel)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        if src and os.path.exists(src) and not os.path.exists(dst):
            os.symlink(src, dst)
    iris_hl = os.path.join(sd, "iris/iris.csv")
    if not os.path.exists(iris_hl):
        src = "/root/reference/h2o-core/src/main/resources/extdata/iris.csv"
        with open(src) as f, open(iris_hl, "w") as out:
            out.writelines(f.readlines()[1:])      # drop the header line
    import zipfile
    pz = os.path.join(sd, "prostate/prostate.csv.zip")
    if not os.path.exists(pz):
        with zipfile.ZipFile(pz, "w") as z:
            z.write(os.path.join(sd, "prostate/prostate.csv"),
                    "prostate.csv")
    from conformance import gen_data
    gen_data.generate_all(sd)
    return sd


def start_backend(port: int = 0) -> int:
    """Same backend contract as server_main.py: TPU by default,
    H2O3TPU_CONF_CPU=1 opts into host CPU — and backend= is mandatory
    for the CPU case because the axon plugin shadows JAX_PLATFORMS."""
    cpu = os.environ.get("H2O3TPU_CONF_CPU") == "1"
    if cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import h2o3_tpu
    h2o3_tpu.init(backend="cpu" if cpu else None)
    from h2o3_tpu.api.server import start_server
    return start_server(port=port)


def connect(port: int):
    import h2o
    h2o.connect(url=f"http://127.0.0.1:{port}", verbose=False,
                strict_version_check=False)
    return h2o


def smoke():
    port = start_backend()
    h2o = connect(port)
    print("connected:", h2o.cluster().cloud_name, h2o.cluster().version)

    tmp = tempfile.mkdtemp(prefix="h2o3tpu_conf_")
    sd = build_smalldata(tmp)
    os.chdir(tmp)

    fr = h2o.import_file(os.path.join(sd, "prostate/prostate.csv"))
    print("imported:", fr.nrow, "x", fr.ncol, fr.names)
    fr["CAPSULE"] = fr["CAPSULE"].asfactor()

    from h2o.estimators.gbm import H2OGradientBoostingEstimator
    m = H2OGradientBoostingEstimator(ntrees=10, max_depth=4, seed=42)
    m.train(x=["AGE", "RACE", "PSA", "GLEASON"], y="CAPSULE",
            training_frame=fr)
    print("trained:", m.model_id)
    print("auc:", m.auc())
    pred = m.predict(fr)
    print("pred:", pred.nrow, pred.names)
    print("SMOKE OK")


def run_pyunit(path: str):
    port = start_backend()
    connect(port)
    tmp = tempfile.mkdtemp(prefix="h2o3tpu_conf_")
    build_smalldata(tmp)
    os.chdir(tmp)
    sys.path.insert(0, os.path.join(REF_PY, "tests"))
    import runpy
    runpy.run_path(path, run_name="__main__")


if __name__ == "__main__":
    cmd = sys.argv[1] if len(sys.argv) > 1 else "smoke"
    if cmd == "smoke":
        smoke()
    elif cmd == "pyunit":
        run_pyunit(sys.argv[2])
