"""Conformance driver: run the curated reference-pyunit subset against
our server and write CONFORMANCE.md.

Usage:
    python conformance/run_all.py            # full curated list
    python conformance/run_all.py gbm        # only entries matching substr

Each pyunit runs unmodified in its own subprocess connected to one shared
server (the reference's scripts/run.py topology: one cloud, many tests).
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = "/root/reference/h2o-py/tests"
ALGOS = os.path.join(TESTS, "testdir_algos")
MISC = os.path.join(TESTS, "testdir_misc")
MUNGING = os.path.join(TESTS, "testdir_munging")

PER_TEST_TIMEOUT = 600
REPORT_NAME = "CONFORMANCE.md"

# Curated subset (VERDICT round-1 item 1: ≥40 from
# testdir_algos/{gbm,glm,deeplearning,kmeans,automl}).  Chosen to need
# only datasets available in this offline environment (prostate, iris,
# synthesized cars/benign/insurance/higgs — conformance/gen_data.py).
PYUNITS = [
    # ---- gbm
    f"{ALGOS}/gbm/pyunit_prostate_gbm.py",
    f"{ALGOS}/gbm/pyunit_iris_gbm.py",
    f"{ALGOS}/gbm/pyunit_bernoulli_gbm.py",
    f"{ALGOS}/gbm/pyunit_cv_cars_gbm.py",
    f"{ALGOS}/gbm/pyunit_weights_gbm.py",
    f"{ALGOS}/gbm/pyunit_weights_var_impGBM.py",
    f"{ALGOS}/gbm/pyunit_mean_residual_deviance_gbm.py",
    f"{ALGOS}/gbm/pyunit_gbm_train_api.py",
    f"{ALGOS}/gbm/pyunit_gbm_grid.py",
    f"{ALGOS}/gbm/pyunit_grid_carsGBM.py",
    f"{ALGOS}/gbm/pyunit_constant_response_gbm.py",
    f"{ALGOS}/gbm/pyunit_staged_predict_gbm.py",
    # ---- glm
    f"{ALGOS}/glm/pyunit_benign_glm.py",
    f"{ALGOS}/glm/pyunit_pubdev_6292_varimp_check.py",
    f"{ALGOS}/glm/pyunit_cv_cars_glm.py",
    f"{ALGOS}/glm/pyunit_solvers_glm.py",
    f"{ALGOS}/glm/pyunit_mean_residual_deviance_glm.py",
    f"{ALGOS}/glm/pyunit_benign_glm_grid.py",
    f"{ALGOS}/glm/pyunit_glm_seed.py",
    f"{ALGOS}/glm/pyunit_coef_and_coef_norm.py",
    f"{ALGOS}/glm/pyunit_link_incompatible_error_glm.py",
    # ---- deeplearning
    f"{ALGOS}/deeplearning/pyunit_iris_basic_deeplearning.py",
    f"{ALGOS}/deeplearning/pyunit_iris_no_hidden.py",
    f"{ALGOS}/deeplearning/pyunit_mean_residual_deviance_deeplearning.py",
    # ---- kmeans
    f"{ALGOS}/kmeans/pyunit_parametersKmeans.py",
    f"{ALGOS}/kmeans/pyunit_constrained_kmeans.py",
    f"{ALGOS}/kmeans/pyunit_benignKmeans.py",
    f"{ALGOS}/kmeans/pyunit_get_modelKmeans.py",
    f"{ALGOS}/kmeans/pyunit_kmeans_cv.py",
    f"{ALGOS}/kmeans/pyunit_kmeans_grid_iris.py",
    # ---- drf
    f"{ALGOS}/rf/pyunit_iris_nfoldsRF.py",
    f"{ALGOS}/rf/pyunit_no_oob_prostateRF.py",
    f"{ALGOS}/rf/pyunit_get_modelRF.py",
    f"{ALGOS}/rf/pyunit_cv_carsRF.py",
    f"{ALGOS}/rf/pyunit_constant_response_rf.py",
    # ---- naive bayes
    f"{ALGOS}/naivebayes/pyunit_irisNB.py",
    f"{ALGOS}/naivebayes/pyunit_irisNB_cv.py",
    # ---- automl
    f"{ALGOS}/automl/pyunit_automl_train.py",
    # ---- api/munging
    f"{MISC}/pyunit_assign.py",
    f"{MISC}/pyunit_colnames.py",
    f"{MUNGING}/pyunit_quantile.py",
    f"{MUNGING}/pyunit_groupby.py",
    f"{MISC}/pyunit_all_confusion_matrix_funcs.py",
    # ---- round-3 breadth: munging (slicing/group-by/sort/string ops)
    # pyunit_sort asserts exact goldens from the reference CreateFrame
    # RNG (unmatchable); the pubdev_4870 variant property-checks
    # sortedness on imported data instead
    f"{MUNGING}/pyunit_pubdev_4870_sort_bug_pubdev_4404_desc.py",
    f"{MUNGING}/pyunit_cbind.py",
    f"{MUNGING}/pyunit_rbind.py",
    f"{MUNGING}/pyunit_unique.py",
    f"{MUNGING}/pyunit_isna.py",
    f"{MUNGING}/pyunit_any_all.py",
    f"{MUNGING}/pyunit_cumsum_cumprod_cummin_cummax.py",
    f"{MUNGING}/pyunit_table.py",
    f"{MUNGING}/pyunit_entropy.py",
    f"{MUNGING}/pyunit_sub_gsub.py",
    f"{MUNGING}/pyunit_strsplit.py",
    f"{MUNGING}/pyunit_toupper_tolower.py",
    f"{MUNGING}/pyunit_substring.py",
    f"{MUNGING}/pyunit_countmatches.py",
    f"{MUNGING}/pyunit_nacnt.py",
    f"{MUNGING}/pyunit_length.py",
    f"{MUNGING}/pyunit_mmult.py",
    f"{MUNGING}/pyunit_prod.py",
    f"{MUNGING}/pyunit_impute.py",
    f"{MUNGING}/pyunit_insert_missing.py",
    f"{MUNGING}/pyunit_difflag1.py",
    f"{MUNGING}/pyunit_rep_len.py",
    f"{MUNGING}/pyunit_categories.py",
    f"{MUNGING}/pyunit_ischaracter_isnumeric.py",
    f"{MUNGING}/pyunit_trim.py",
    f"{MUNGING}/pyunit_op_precedence.py",
    f"{MUNGING}/pyunit_in.py",
    f"{MUNGING}/pyunit_count_temps.py",
    f"{MUNGING}/pyunit_runif.py",
    f"{MUNGING}/pyunit_ifelse.py",
    # ---- round-3 breadth: misc metrics / model introspection
    f"{MISC}/pyunit_metric_accessors.py",
    f"{MISC}/pyunit_model_summary.py",
    f"{MISC}/pyunit_varimp.py",
    f"{MISC}/pyunit_create_frame.py",
    f"{MISC}/pyunit_frame_show.py",
    # ---- round-3: glm multinomial parity (IRLSM solver)
    f"{ALGOS}/glm/pyunit_PUBDEV_6062_multinomial_coeffNames.py",
    # ---- round-4: GLM family tail (VERDICT r3 missing #6) — the
    # negativebinomial grid (theta x alpha), the ordinal
    # predict-vs-probs consistency bug test, and the quasibinomial
    # rejection contract for non-GLM/GBM algos
    f"{ALGOS}/glm/pyunit_PUBDEV_6349_negbinomial_gridsearch.py",
    f"{ALGOS}/glm/pyunit_pubdev_8194_ordinal_fail.py",
    f"{MISC}/pyunit_distribution_check.py",
]


def start_server():
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "conformance", "server_main.py")],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=REPO)
    import selectors
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    port = None
    t0 = time.time()
    while time.time() - t0 < 120:
        if proc.poll() is not None:
            break                       # child died — fail fast
        if not sel.select(timeout=1.0):
            continue                    # nothing to read yet
        line = proc.stdout.readline()
        m = re.match(r"PORT=(\d+)", line or "")
        if m:
            port = int(m.group(1))
            break
    if port is None:
        proc.kill()
        raise RuntimeError("server failed to start")
    return proc, port


def main():
    filt = sys.argv[1] if len(sys.argv) > 1 else ""
    # filtered runs must NEVER overwrite the full-suite report: round 2
    # committed a 5-test GBM-only CONFORMANCE.md over the 38-test table
    global REPORT_NAME
    if filt:
        REPORT_NAME = "CONFORMANCE.partial.md"
    filts = [f for f in filt.split(",") if f]
    units = [u for u in PYUNITS if not filts or any(f in u for f in filts)]
    workdir = tempfile.mkdtemp(prefix="h2o3tpu_conf_")
    sys.path.insert(0, REPO)
    from conformance.harness import build_smalldata
    build_smalldata(workdir)

    proc, port = start_server()
    url = f"http://127.0.0.1:{port}"
    results = []
    try:
        for u in units:
            if proc.poll() is not None:      # backend died — restart it
                print("  [server died; restarting]", flush=True)
                proc, port = start_server()
                url = f"http://127.0.0.1:{port}"
            name = "/".join(u.split("/")[-2:])
            t0 = time.time()
            try:
                r = subprocess.run(
                    [sys.executable,
                     os.path.join(REPO, "conformance", "run_one.py"),
                     url, u, workdir],
                    capture_output=True, text=True,
                    timeout=PER_TEST_TIMEOUT, cwd=REPO)
                ok = r.returncode == 0 and "PYUNIT-PASS" in r.stdout
                tail = (r.stderr or r.stdout).strip().splitlines()[-8:]
            except subprocess.TimeoutExpired:
                ok, tail = False, ["TIMEOUT"]
            dt = time.time() - t0
            results.append((name, ok, dt, tail))
            print(f"{'PASS' if ok else 'FAIL'}  {name}  ({dt:.1f}s)",
                  flush=True)
            # clear the cloud between pyunits (scripts/run.py resets
            # state too): leaked frames/models otherwise accumulate in
            # HBM until the chip ResourceExhausts mid-suite (~60 tests)
            try:
                import urllib.request
                req = urllib.request.Request(f"{url}/3/DKV",
                                             method="DELETE")
                urllib.request.urlopen(req, timeout=60).read()
            except Exception as e:
                print(f"  [dkv clear failed: {e}]", flush=True)
            if not ok:
                for ln in tail:
                    print("      " + ln)
            write_report(results, total=len(units))  # incremental
    finally:
        proc.kill()

    npass = sum(1 for _, ok, _, _ in results if ok)
    print(f"\n{npass}/{len(results)} passed")
    write_report(results, total=len(units))


def write_report(results, total=None):
    npass = sum(1 for _, ok, _, _ in results if ok)
    lines = [
        "# CONFORMANCE — reference h2o-py pyunits vs h2o3-tpu",
        "",
        "The UNMODIFIED reference client (`/root/reference/h2o-py`, via the",
        "tiny `future` shim in `conformance/shims/`) runs curated reference",
        "pyunits against this server (`python conformance/run_all.py`).",
        "Datasets: real in-tree files (prostate, iris) symlinked at runtime;",
        "schema-compatible synthetic stand-ins elsewhere",
        "(`conformance/gen_data.py`). Tests needing data that does not",
        "exist in this offline image are excluded. This file is ALWAYS",
        "the full curated suite; filtered runs write",
        "CONFORMANCE.partial.md instead.",
        "",
        f"**Result: {npass}/{len(results)} passing** "
        f"({time.strftime('%Y-%m-%d')})"
        + (f" — **RUN IN PROGRESS: {len(results)}/{total} executed**"
           if total and len(results) < total else ""),
        "",
        "| pyunit | status | time |",
        "|---|---|---|",
    ]
    for name, ok, dt, tail in results:
        # keep the whole assertion line: round-2's 80-char cut turned
        # "...22.543315116995075, and 22.542878951149426" into "...and
        # 2", making a 2e-5 float mismatch read as a 10x bug
        status = "pass" if ok else "FAIL — `" + \
            (tail[-1][:200].replace("|", "/") if tail else "?") + "`"
        lines.append(f"| {name} | {status} | {dt:.1f}s |")
    with open(os.path.join(REPO, REPORT_NAME), "w") as f:
        f.write("\n".join(lines) + "\n")


if __name__ == "__main__":
    main()
