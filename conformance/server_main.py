"""Conformance backend: boot the cloud + REST server, print the port."""

import faulthandler
import os
import signal
import sys
import time

faulthandler.register(signal.SIGUSR1)   # kill -USR1 <pid> dumps stacks

# Default TPU: per-test wallclock is compile+dispatch bound and the
# tunneled chip clears the many-model pyunits ~4x faster than this
# 1-core host (round-2 timings were in fact TPU timings — JAX_PLATFORMS
# was being shadowed). H2O3TPU_CONF_CPU=1 opts back into host CPU for
# parallel/offline runs.
_cpu = os.environ.get("H2O3TPU_CONF_CPU") == "1"
if _cpu:
    os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import h2o3_tpu                               # noqa: E402
# backend= is mandatory: the axon TPU plugin shadows JAX_PLATFORMS=cpu,
# so init() without it silently lands the whole conformance run on the
# single tunneled chip (contention + ResourceExhausted flakes)
h2o3_tpu.init(backend="cpu" if _cpu else None)
from h2o3_tpu.api.server import start_server  # noqa: E402

port = start_server(port=int(sys.argv[1]) if len(sys.argv) > 1 else 0)
print(f"PORT={port}", flush=True)
while True:
    time.sleep(3600)
