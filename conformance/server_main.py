"""Conformance backend: boot the cloud + REST server, print the port."""

import os
import sys
import time

# conformance is a correctness surface, not a perf surface: run the
# backend on host CPU so parallel conformance runs never contend for the
# single tunneled TPU chip (override with H2O3TPU_CONF_TPU=1)
_cpu = os.environ.get("H2O3TPU_CONF_TPU") != "1"
if _cpu:
    os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import h2o3_tpu                               # noqa: E402
# backend= is mandatory: the axon TPU plugin shadows JAX_PLATFORMS=cpu,
# so init() without it silently lands the whole conformance run on the
# single tunneled chip (contention + ResourceExhausted flakes)
h2o3_tpu.init(backend="cpu" if _cpu else None)
from h2o3_tpu.api.server import start_server  # noqa: E402

port = start_server(port=int(sys.argv[1]) if len(sys.argv) > 1 else 0)
print(f"PORT={port}", flush=True)
while True:
    time.sleep(3600)
